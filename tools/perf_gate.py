"""Performance regression gate over the BENCH_r*.json trajectory.

The observatory's verdict half: ``bench.py`` measures, the rounds
accumulate as ``BENCH_r0N.json``, and THIS turns the trajectory into
an exit code — the same sensor→verdict discipline the telemetry ring
(PR 4) and the watchdog (PR 7) apply to training health, applied to
performance.  No jax import, stdlib only: the gate must run on any CI
box in milliseconds.

    python tools/perf_gate.py             # gate: exit 1 on regression
    python tools/perf_gate.py --report    # report-only: always exit 0
    python tools/perf_gate.py --json      # machine-readable verdicts

Budget: ``tools/perf_budget.json`` maps a dotted metric path (into
the round's parsed bench line, e.g. ``extra.resnet50_mfu``) to a
floor (or ceiling, for lower-is-better metrics) plus a per-metric
noise band.  The noise bands encode benchlib's amortized-timing
methodology: tracked train metrics repeat within a few percent
between windows, so only an ABOVE-NOISE drop is a regression —
within-band wobble reports as ``ok (within noise)``.

Two checks per metric, both noise-banded:

- **budget**: the newest hardware measurement vs its committed
  floor/ceiling — the "never ship slower than this" line, restamped
  from each accepted hardware window;
- **trajectory**: the newest measurement vs the best previous
  hardware round — catches a slide the budget's slack would hide.

A metric the NEWEST hardware round stopped reporting grades
``stale`` and fails the gate: a perf loss that manifests as a crashed
bench leg (the BENCH_r05 flash shape) must not read as green by
comparing an older round's value against the floor.

Only real hardware rounds count (``backend`` "tpu" or "tpu-cached",
positive value): the CPU-fallback liveness lines prove the harness,
not performance, and a cached round re-served across windows compares
equal to itself (no false regression while the tunnel is down).

**Structural rows** (``"source": "ledger"`` in the budget) grade from
the committed apexcost ledger (``apex_tpu/lint/cost/ledger.json``)
instead of BENCH rounds: ``ledger_entry`` names the cost card,
``ledger_field`` the dotted field (e.g.
``extras.serving_hbm_bytes_per_slot``).  Their values are
deterministic facts of the tree, so they default to a ZERO noise band
and gate in auto mode regardless of hardware-round recency; only a
forced ``--report`` waives them.  A vanished card or field grades
``stale`` (gating) — a deleted ledger must not read as green.

An **empty trajectory** (no ``BENCH_r*.json`` with a parsed bench
line at all) grades ``no-rounds`` explicitly: one line saying there is
nothing to grade, exit 0 in auto/report mode (a forced ``--gate``
exits 1 — an empty record cannot defend a budget).

**Gating is automatic**: with neither ``--report`` nor ``--gate``, the
gate flips on exactly when the newest BENCH round is a hardware round
measured AFTER the budget's ``stamped_at`` date — fresh hardware
numbers must be defended, while the cached pre-flat-pipeline rounds
(whose capture date the budget was stamped from) stay report-only so
they cannot block the PRs that will re-measure them.  The chosen mode
and its reason are always printed.

Every hardware round additionally prints its **measurement age**
(capture timestamp + days since) — the cached rounds re-serve the
2026-07-31 window, and that staleness should be visible in every
``tools/check.sh`` run, not only in ROADMAP prose.  When the newest
hardware data predates the budget's ``stamped_at`` by more than
``--stale-days`` (default 14), the gate prints a WARNING: the budget
is defending numbers nobody has re-measured in that long.  Neither
the age lines nor the warning change the exit code.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET_PATH = os.path.join(_ROOT, "tools", "perf_budget.json")
# the apexcost ledger: committed static cost cards, the source for
# budget rows marked {"source": "ledger"}
LEDGER_PATH = os.path.join(_ROOT, "apex_tpu", "lint", "cost",
                           "ledger.json")

_HW_BACKENDS = {"tpu", "tpu-cached"}


def load_rounds(root: str = _ROOT) -> List[Tuple[int, dict]]:
    """[(round_number, parsed bench line), ...] sorted by round, for
    every round whose artifact holds a parseable bench line."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            out.append((int(m.group(1)), parsed))
    out.sort()
    return out


def _numeric(v) -> float:
    """Best-effort float; malformed values read as 0 (a hand-edited
    artifact must degrade to "not a hardware round", not a traceback
    aborting the whole check run)."""
    try:
        return float(v or 0)
    except (TypeError, ValueError):
        return 0.0


def hardware_rounds(rounds: List[Tuple[int, dict]]) -> List[Tuple[int, dict]]:
    return [(n, p) for n, p in rounds
            if p.get("backend") in _HW_BACKENDS
            and _numeric(p.get("value")) > 0]


def metric_value(parsed: dict, dotted: str) -> Optional[float]:
    """Resolve ``"extra.resnet50_mfu"``-style paths; None when any
    segment is missing or the leaf is not a number."""
    node = parsed
    for seg in dotted.split("."):
        if not isinstance(node, dict) or seg not in node:
            return None
        node = node[seg]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def _check(name: str, spec: dict,
           rounds: List[Tuple[int, dict]]) -> dict:
    """One metric's verdict dict (status: ok | regression | no-data)."""
    direction = spec.get("direction", "higher")
    noise_pct = float(spec.get("noise_pct", 5.0))
    limit = spec.get("floor" if direction == "higher" else "ceiling")
    series = [(n, metric_value(p, name)) for n, p in rounds]
    series = [(n, v) for n, v in series if v is not None]
    verdict = {"metric": name, "direction": direction,
               "noise_pct": noise_pct, "limit": limit,
               "rounds": [n for n, _ in series]}
    if not series:
        verdict.update(status="no-data",
                       detail="no hardware round reports this metric")
        return verdict
    newest_round, newest = series[-1]
    verdict.update(newest=newest, newest_round=newest_round)
    if rounds and newest_round != rounds[-1][0]:
        # the newest hardware round stopped reporting this metric — a
        # perf loss that manifests as a crashed leg must not read as
        # green; grading r(N-1)'s value against the floor would mask it
        verdict.update(
            status="stale",
            detail=f"newest hardware round r{rounds[-1][0]:02d} does "
                   f"not report this metric (last seen "
                   f"r{newest_round:02d}) — a crashed bench leg "
                   "cannot pass the gate")
        return verdict
    worse = ((lambda a, b: a < b) if direction == "higher"
             else (lambda a, b: a > b))
    band = 1.0 - noise_pct / 100.0
    failures = []

    if limit is not None:
        # budget check: newest vs floor/ceiling, noise-banded
        lim = float(limit)
        threshold = lim * band if direction == "higher" else lim / band
        if worse(newest, threshold):
            failures.append(
                f"newest {newest:g} (r{newest_round:02d}) breaches "
                f"{'floor' if direction == 'higher' else 'ceiling'} "
                f"{lim:g} beyond the {noise_pct:g}% noise band")

    prev = [v for _, v in series[:-1]]
    if prev:
        best_prev = max(prev) if direction == "higher" else min(prev)
        threshold = (best_prev * band if direction == "higher"
                     else best_prev / band)
        verdict["best_prev"] = best_prev
        if worse(newest, threshold):
            failures.append(
                f"newest {newest:g} (r{newest_round:02d}) regressed "
                f"beyond {noise_pct:g}% noise vs best prior {best_prev:g}")

    verdict["status"] = "regression" if failures else "ok"
    if failures:
        verdict["detail"] = "; ".join(failures)
    return verdict


def load_ledger(path: str = LEDGER_PATH) -> Optional[dict]:
    """The committed apexcost ledger, or None when absent/unreadable
    (the --cost lint gate owns failing on THAT; here a missing ledger
    just grades its rows stale)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _check_ledger(name: str, spec: dict,
                  ledger_doc: Optional[dict]) -> dict:
    """Verdict for a STRUCTURAL budget row graded from the apexcost
    ledger instead of BENCH rounds.  These are deterministic program
    facts (bytes per decode slot, collective payload per step), so the
    noise band defaults to zero and the verdict gates in auto mode
    regardless of hardware-round recency — the value comes from the
    committed tree, not from a measurement."""
    direction = spec.get("direction", "lower")
    noise_pct = float(spec.get("noise_pct", 0.0))
    limit = spec.get("floor" if direction == "higher" else "ceiling")
    verdict = {"metric": name, "direction": direction,
               "noise_pct": noise_pct, "limit": limit,
               "source": "ledger", "rounds": [],
               "ledger_entry": spec.get("ledger_entry"),
               "ledger_field": spec.get("ledger_field")}
    card = (ledger_doc or {}).get("cards", {}) \
        .get(spec.get("ledger_entry"))
    value = metric_value(card, spec.get("ledger_field", "")) \
        if isinstance(card, dict) else None
    if value is None:
        # a vanished card/field must not read as green — same
        # crashed-leg discipline as the stale trajectory check
        verdict.update(
            status="stale",
            detail=f"ledger entry {spec.get('ledger_entry')!r} does "
                   f"not report {spec.get('ledger_field')!r} "
                   "(ledger missing, stale or field removed) — "
                   "regenerate with `python -m apex_tpu.lint "
                   "--write-ledger`")
        return verdict
    verdict["newest"] = value
    worse = ((lambda a, b: a < b) if direction == "higher"
             else (lambda a, b: a > b))
    band = 1.0 - noise_pct / 100.0
    if limit is not None:
        lim = float(limit)
        threshold = lim * band if direction == "higher" else lim / band
        if worse(value, threshold):
            verdict.update(
                status="regression",
                detail=f"ledger value {value:g} breaches "
                       f"{'floor' if direction == 'higher' else 'ceiling'} "
                       f"{lim:g} (noise band {noise_pct:g}%) — an "
                       "intended change must restamp the budget row "
                       "alongside --write-ledger")
            return verdict
    verdict["status"] = "ok"
    return verdict


def parse_when(when) -> Optional["datetime.datetime"]:
    """Parse the bench stamp format (``2026-07-31T03:41:18Z``); None
    for anything else — a malformed stamp degrades to "no age", never
    a traceback out of the gate."""
    import datetime
    try:
        return datetime.datetime.strptime(when, "%Y-%m-%dT%H:%M:%SZ")
    except (TypeError, ValueError):
        return None


def age_days(when, now=None) -> Optional[int]:
    """Whole days between a bench capture stamp and ``now`` (UTC)."""
    import datetime
    t = parse_when(when)
    if t is None:
        return None
    if now is None:
        now = datetime.datetime.now(datetime.timezone.utc) \
            .replace(tzinfo=None)
    return (now - t).days


def round_when(parsed: dict) -> Optional[str]:
    """ISO capture timestamp of one bench line: live rounds carry
    ``measured_at``; cached rounds re-serve the original window's
    stamp as ``extra.cached_measured_at``."""
    when = parsed.get("measured_at")
    if isinstance(when, str) and when:
        return when
    extra = parsed.get("extra")
    if isinstance(extra, dict):
        when = extra.get("cached_measured_at")
        if isinstance(when, str) and when:
            return when
    return None


def choose_mode(budget: dict,
                rounds: List[Tuple[int, dict]]) -> Tuple[bool, str]:
    """(gating, reason) for auto mode: gate exactly when the newest
    BENCH round is a hardware round measured after the budget's
    ``stamped_at`` (ISO strings compare lexicographically).  Anything
    unprovable — no rounds, a CPU newest round, missing timestamps —
    stays report-only, loudly."""
    if not rounds:
        return False, "report-only: no BENCH rounds found"
    n, parsed = rounds[-1]
    if parsed.get("backend") not in _HW_BACKENDS \
            or _numeric(parsed.get("value")) <= 0:
        return False, (f"report-only: newest round r{n:02d} is not a "
                       "hardware round")
    when = round_when(parsed)
    stamped = budget.get("stamped_at")
    if not when or not isinstance(stamped, str) or not stamped:
        return False, (f"report-only: cannot compare newest round "
                       f"r{n:02d} ({when or 'no timestamp'}) against "
                       f"budget stamp ({stamped or 'no stamped_at'})")
    if when > stamped:
        return True, (f"gating: newest hardware round r{n:02d} "
                      f"({when}) postdates the budget stamp "
                      f"({stamped}) — fresh numbers are defended")
    return False, (f"report-only: newest hardware round r{n:02d} "
                   f"({when}) does not postdate the budget stamp "
                   f"({stamped}); the budget already covers it")


def evaluate(budget: dict, rounds: List[Tuple[int, dict]],
             ledger_doc: Optional[dict] = None) -> List[dict]:
    hw = hardware_rounds(rounds)
    out = []
    for name, spec in sorted(budget.get("metrics", {}).items()):
        if spec.get("source") == "ledger":
            out.append(_check_ledger(name, spec, ledger_doc))
        else:
            out.append(_check(name, spec, hw))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH trajectory regression gate "
                    "(tools/perf_budget.json)")
    ap.add_argument("--budget", default=BUDGET_PATH)
    ap.add_argument("--root", default=_ROOT,
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--report", action="store_true",
                    help="force report-only: print verdicts, always "
                         "exit 0")
    ap.add_argument("--gate", action="store_true",
                    help="force gating regardless of round/stamp dates")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--stale-days", type=int, default=14,
                    help="warn when the newest hardware data predates "
                         "the budget stamp by more than this many "
                         "days (warning only — never the exit code)")
    args = ap.parse_args(argv)

    try:
        with open(args.budget, encoding="utf-8") as f:
            budget = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot read budget {args.budget}: {e}",
              file=sys.stderr)
        return 2
    rounds = load_rounds(args.root)
    ledger_doc = load_ledger()
    ledger_rows = {n for n, s in budget.get("metrics", {}).items()
                   if s.get("source") == "ledger"}
    if not rounds and ledger_rows:
        # structural ledger rows grade even on an empty BENCH
        # trajectory — they come from the committed tree, not from
        # measurements; hardware rows still report no-rounds below
        pass
    elif not rounds:
        # an EMPTY trajectory is its own explicit verdict, not an
        # N-way "no hardware round reports this metric" chorus: there
        # is literally nothing to grade, say so in one line and exit
        # clean (auto/report — a forced --gate still refuses to pass
        # silently, there is nothing defending the budget)
        reason = ("no-rounds: BENCH trajectory is empty (no "
                  "BENCH_r*.json with a parsed bench line under "
                  f"{args.root}) — nothing to grade; run bench.py on "
                  "hardware to start the trajectory")
        if args.json:
            print(json.dumps({"verdicts": [], "hardware_rounds": [],
                              "regressions": 0, "gating": args.gate,
                              "status": "no-rounds",
                              "mode_reason": reason}))
        else:
            print(f"perf_gate: {reason}")
        return 1 if args.gate else 0
    if args.report:
        gating, reason = False, "report-only: forced by --report"
    elif args.gate:
        gating, reason = True, "gating: forced by --gate"
    else:
        gating, reason = choose_mode(budget, rounds)
    verdicts = evaluate(budget, rounds, ledger_doc)
    # stale (metric vanished from the newest hardware round) gates
    # like a regression: a crashed leg must not pass
    regressions = [v for v in verdicts
                   if v["status"] in ("regression", "stale")]
    # ledger-sourced rows gate unconditionally in auto mode: their
    # values are deterministic facts of the committed tree, so there
    # is no "stale hardware" excuse — only --report waives them
    structural = [v for v in regressions
                  if v.get("source") == "ledger"]

    # measurement ages: when each hardware round's data was actually
    # captured (cached rounds re-serve their original window's stamp),
    # plus a staleness warning when the newest hardware data predates
    # the budget stamp by more than --stale-days — report-only, the
    # exit code never depends on either
    hw = hardware_rounds(rounds)
    ages = [{"round": n, "backend": p.get("backend"),
             "measured_at": round_when(p),
             "age_days": age_days(round_when(p))} for n, p in hw]
    stale_warning = None
    if hw:
        stamped_dt = parse_when(budget.get("stamped_at"))
        newest_dt = parse_when(round_when(hw[-1][1]))
        if stamped_dt and newest_dt:
            behind = (stamped_dt - newest_dt).days
            if behind > args.stale_days:
                stale_warning = (
                    f"WARNING: newest hardware data "
                    f"({round_when(hw[-1][1])}) predates the budget "
                    f"stamp ({budget.get('stamped_at')}) by {behind} "
                    f"days (> {args.stale_days}) — the budget defends "
                    "numbers nobody has re-measured; run bench.py on "
                    "hardware")

    if args.json:
        print(json.dumps({"verdicts": verdicts,
                          "hardware_rounds":
                          [n for n, _ in hw],
                          "measurement_ages": ages,
                          "stale_warning": stale_warning,
                          "regressions": len(regressions),
                          "structural_regressions": len(structural),
                          "gating": gating, "mode_reason": reason}))
    else:
        print(f"perf_gate: {len(hw)} hardware round(s) "
              f"{[n for n, _ in hw]} of {len(rounds)} total")
        for a in ages:
            if a["measured_at"]:
                line = (f"  r{a['round']:02d} {a['backend']}: "
                        f"measured {a['measured_at']}")
                if a["age_days"] is not None:
                    line += f" ({a['age_days']} day(s) ago)"
            else:
                line = (f"  r{a['round']:02d} {a['backend']}: "
                        "no capture timestamp")
            print(line)
        if stale_warning:
            print(f"perf_gate: {stale_warning}")
        print(f"perf_gate: {reason}")
        for v in verdicts:
            line = f"  {v['status']:<10} {v['metric']}"
            if v.get("newest") is not None:
                line += f"  newest={v['newest']:g}"
                if v.get("newest_round") is not None:
                    line += f" (r{v['newest_round']:02d})"
                elif v.get("source") == "ledger":
                    line += " (ledger)"
            if v.get("limit") is not None:
                kind = ("floor" if v["direction"] == "higher"
                        else "ceiling")
                line += f"  {kind}={v['limit']:g}"
            if v.get("detail"):
                line += f"  [{v['detail']}]"
            print(line)
        if regressions:
            tag = "" if gating else (
                " (report-only, not gating)" if not structural
                else " (structural ledger row(s) gate regardless)")
            print(f"perf_gate: {len(regressions)} above-noise "
                  f"regression(s){tag}")
        else:
            print("perf_gate: trajectory clean")
    if structural and not args.report:
        return 1
    return 0 if (not gating or not regressions) else 1


if __name__ == "__main__":
    raise SystemExit(main())
