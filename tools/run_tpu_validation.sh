#!/bin/bash
# Serial TPU validation: smoke suite, then bench. ONE TPU client at a
# time; nothing here kills a TPU-attached process (a killed client
# wedges the single-client tunnel for a long time — see
# docs/kernels.md dispatch note and tests/test_tpu_smoke.py header).
set -u
cd "$(dirname "$0")/.."

echo "== TPU smoke suite =="
APEX_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -v \
    > /tmp/smoke_tpu.log 2>&1
smoke_rc=$?
tail -5 /tmp/smoke_tpu.log
echo "smoke rc=$smoke_rc"

echo "== bench =="
python bench.py > /tmp/bench_tpu.json 2>/tmp/bench_tpu.err
bench_rc=$?
cat /tmp/bench_tpu.json
echo "bench rc=$bench_rc"

exit $(( smoke_rc != 0 || bench_rc != 0 ? 1 : 0 ))
