#!/bin/bash
# Serial TPU validation: everything the round needs from ONE tunnel
# window, strictly sequentially (the axon tunnel admits ONE client at
# a time; nothing here kills a TPU-attached process — a killed client
# wedges the tunnel for a long time, see tests/test_tpu_smoke.py).
#
# Phases (each its own client, 60s etiquette gap between):
#   1. bounded probe            — abort early if the tunnel is down
#   2. TPU smoke suite          — every Pallas kernel non-interpreted
#                                 vs its oracle (target: 37/37)
#   3. kernel bench             — per-kernel vs XLA oracle timings ->
#                                 bench_kernels.csv + dispatch prefs
#   4. bench.py                 — tracked metrics (ResNet-50 imgs/sec,
#                                 BERT-L step, MFU) -> bench JSON
#
# Artifacts land in tools/artifacts/ for committing.
set -u
cd "$(dirname "$0")/.."
ART=tools/artifacts
mkdir -p "$ART"

echo "== probe =="
# bounded probe first: a wedged tunnel blocks jax.devices() forever, and
# letting pytest hit that just produces an unkillable client
if ! timeout 180 python -c "import jax; print(jax.devices())"; then
    echo "probe: tunnel not available (timeout/err); aborting validation"
    exit 2
fi
sleep 60    # etiquette: gap between tunnel clients

echo "== TPU smoke suite =="
# NO timeout here: killing a TPU-attached pytest wedges the tunnel (see
# header); the bounded probe above already guards the hang case that
# matters (backend init), and bench.py has its own internal watchdogs
APEX_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -v \
    > "$ART/smoke_tpu.log" 2>&1
smoke_rc=$?
tail -5 "$ART/smoke_tpu.log"
# pytest exits 0 on all-skipped (backend never initialized): that is a
# FAILED validation, not a pass
if ! grep -qE "[0-9]+ passed" "$ART/smoke_tpu.log"; then
    echo "smoke: no tests actually ran (all skipped or collection failed)"
    smoke_rc=1
fi
echo "smoke rc=$smoke_rc"

sleep 60    # gap before the next client

echo "== kernel bench (csv + dispatch prefs) =="
# also uncapped: it is a TPU-attached client
python tools/kernel_bench.py --csv "$ART/bench_kernels.csv" \
    --write-prefs > "$ART/bench_kernels.jsonl" 2>"$ART/bench_kernels.err"
kb_rc=$?
tail -3 "$ART/bench_kernels.jsonl"
# kernel_bench exits 0 when it skips off-TPU (tunnel dropped between
# phases): no TPU-labeled rows means the phase did NOT validate
if ! grep -q '"backend": "tpu"' "$ART/bench_kernels.jsonl"; then
    echo "kernel_bench: no TPU rows (backend fell back?); phase failed"
    kb_rc=1
fi
echo "kernel_bench rc=$kb_rc"

sleep 60    # gap before the next client

echo "== bench =="
python bench.py > "$ART/bench_tpu.json" 2>"$ART/bench_tpu.err"
cat "$ART/bench_tpu.json"
# bench.py always exits 0 by design; judge the JSON instead
bench_rc=$(ART="$ART" python - <<'EOF'
import json, os
try:
    out = json.load(open(os.path.join(os.environ["ART"],
                                      "bench_tpu.json")))
    ok = (out.get("backend") == "tpu" and float(out.get("value", 0)) > 0
          and not out.get("errors"))
    print(0 if ok else 1)
except Exception:
    print(1)
EOF
)
echo "bench rc=$bench_rc"

echo "== summary =="
echo "smoke=$smoke_rc kernel_bench=$kb_rc bench=$bench_rc  (0 = pass)"
echo "artifacts in $ART/: smoke_tpu.log bench_kernels.{csv,jsonl} bench_tpu.json"
echo "next: review dispatch_prefs.json + commit artifacts"

exit $(( smoke_rc != 0 || kb_rc != 0 || bench_rc != 0 ? 1 : 0 ))
