#!/bin/bash
# Serial TPU validation: smoke suite, then bench. ONE TPU client at a
# time; nothing here kills a TPU-attached process (a killed client
# wedges the single-client tunnel for a long time — see
# tests/test_tpu_smoke.py header).
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
# bounded probe first: a wedged tunnel blocks jax.devices() forever, and
# letting pytest hit that just produces an unkillable client
if ! timeout 180 python -c "import jax; print(jax.devices())"; then
    echo "probe: tunnel not available (timeout/err); aborting validation"
    exit 2
fi
sleep 60    # etiquette: gap between tunnel clients

echo "== TPU smoke suite =="
# NO timeout here: killing a TPU-attached pytest wedges the tunnel (see
# header); the bounded probe above already guards the hang case that
# matters (backend init), and bench.py has its own internal watchdogs
APEX_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -v \
    > /tmp/smoke_tpu.log 2>&1
smoke_rc=$?
tail -5 /tmp/smoke_tpu.log
# pytest exits 0 on all-skipped (backend never initialized): that is a
# FAILED validation, not a pass
if ! grep -qE "[0-9]+ passed" /tmp/smoke_tpu.log; then
    echo "smoke: no tests actually ran (all skipped or collection failed)"
    smoke_rc=1
fi
echo "smoke rc=$smoke_rc"

sleep 60    # gap before the next client

echo "== bench =="
python bench.py > /tmp/bench_tpu.json 2>/tmp/bench_tpu.err
cat /tmp/bench_tpu.json
# bench.py always exits 0 by design; judge the JSON instead
bench_rc=$(python - <<'EOF'
import json
try:
    out = json.load(open("/tmp/bench_tpu.json"))
    ok = (out.get("backend") == "tpu" and float(out.get("value", 0)) > 0
          and not out.get("errors"))
    print(0 if ok else 1)
except Exception:
    print(1)
EOF
)
echo "bench rc=$bench_rc"

exit $(( smoke_rc != 0 || bench_rc != 0 ? 1 : 0 ))
