#!/bin/bash
# TPU validation entry point — thin wrapper over the ONE-SESSION
# validator (tools/one_session_validation.py).
#
# HISTORY: this script used to run probe -> smoke -> kernel bench ->
# sweep -> bench -> trace as SEPARATE tunnel clients with etiquette
# gaps.  Round-4 field data (tools/artifacts/validation_run.log,
# 2026-07-31) showed the axon relay admits only the FIRST client after
# a relay restart: the probe attached in 4s, then the smoke suite hung
# in backend init for 25 minutes and every later phase fell back to
# CPU.  Probe-first DESIGN BURNS THE WINDOW.  All phases now run
# inside one python process — one client, one session, every artifact.
#
# Phase stamps ($ART/.phase_<name>.ok) are unchanged: re-running skips
# phases that already passed on hardware, so a second window resumes
# where the first ended.
set -u
cd "$(dirname "$0")/.."
ART=tools/artifacts
mkdir -p "$ART"

ts() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

echo "$(ts) == one-session validation =="
# No timeout: killing a TPU-attached client wedges the tunnel (round-2
# caveat, PARITY.md).  A burned/absent session resolves itself: the
# PJRT plugin gives up internally (~25 min observed) and the validator
# exits 3 without touching hardware.
python tools/one_session_validation.py
rc=$?
echo "$(ts) validator rc=$rc"

echo "$(ts) == summary =="
all_ok=0
for p in smoke kernel_bench sweep_attn bench trace; do
    if [ -f "$ART/.phase_$p.ok" ]; then
        echo "  $p: PASS ($(cat "$ART/.phase_$p.ok"))"
    else
        echo "  $p: INCOMPLETE"
        all_ok=1
    fi
done
echo "artifacts in $ART/: smoke_tpu.log bench_kernels.{csv,jsonl} sweep_attn.{csv,jsonl} bench_tpu.json trace/"
exit $all_ok
