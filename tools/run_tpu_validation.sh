#!/bin/bash
# Serial TPU validation: everything the round needs from ONE tunnel
# window, strictly sequentially (the axon tunnel admits ONE client at
# a time; nothing here kills a TPU-attached process — a killed client
# wedges the tunnel for a long time, see tests/test_tpu_smoke.py).
#
# Phases (each its own client, 60s etiquette gap between):
#   1. bounded probe            — abort early if the tunnel is down
#   2. TPU smoke suite          — every Pallas kernel non-interpreted
#                                 vs its oracle (target: 37/37)
#   3. kernel bench             — per-kernel vs XLA oracle timings ->
#                                 bench_kernels.csv + dispatch prefs
#   4. attention geometry sweep — kernel_bench --sweep-attn -> best
#                                 APEX_TPU_ATTN_BLOCK_CAP per shape
#   5. bench.py                 — tracked metrics (ResNet-50 imgs/sec,
#                                 BERT-L step, MFU) -> bench JSON
#   6. profiler trace           — profile_step.py on the north-star
#                                 step -> trace dir + summary
#
# CHECKPOINTED: each phase that passes writes $ART/.phase_<name>.ok.
# Re-running the script skips phases whose stamp exists, so a tunnel
# that drops mid-run resumes where it left off instead of repeating
# TPU work (windows are the scarcest resource in the project).
# Delete the stamps (or the artifacts dir) to force a full re-run.
set -u
cd "$(dirname "$0")/.."
ART=tools/artifacts
mkdir -p "$ART"

ts() { date -u +"%Y-%m-%dT%H:%M:%SZ"; }

phase_done() { [ -f "$ART/.phase_$1.ok" ]; }
mark_done()  { ts > "$ART/.phase_$1.ok"; }

# Etiquette gap between tunnel clients — only needed after a phase that
# actually attached a client, not after a skipped phase.
GAP=60
need_gap=0
gap() { if [ "$need_gap" = 1 ]; then sleep "$GAP"; fi; need_gap=1; }

all_done=1
for p in smoke kernel_bench sweep_attn bench trace; do
    phase_done "$p" || all_done=0
done
if [ "$all_done" = 1 ]; then
    echo "$(ts) all phases already stamped in $ART — nothing to do"
    exit 0
fi

echo "$(ts) == probe =="
# bounded probe first: a wedged tunnel blocks jax.devices() forever, and
# letting pytest hit that just produces an unkillable client
if ! timeout 180 python -c "import jax; print(jax.devices())"; then
    echo "$(ts) probe: tunnel not available (timeout/err); aborting validation"
    exit 2
fi
need_gap=1

if phase_done smoke; then
    echo "$(ts) == TPU smoke suite == (stamped, skipping)"
else
    gap
    echo "$(ts) == TPU smoke suite =="
    # NO timeout here: killing a TPU-attached pytest wedges the tunnel
    # (see header); the bounded probe above already guards the hang case
    # that matters (backend init), and bench.py has internal watchdogs
    APEX_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py -v \
        > "$ART/smoke_tpu.log" 2>&1
    smoke_rc=$?
    tail -5 "$ART/smoke_tpu.log"
    # pytest exits 0 on all-skipped (backend never initialized): that is
    # a FAILED validation, not a pass
    if ! grep -qE "[0-9]+ passed" "$ART/smoke_tpu.log"; then
        echo "$(ts) smoke: no tests actually ran (all skipped or collection failed)"
        smoke_rc=1
    fi
    echo "$(ts) smoke rc=$smoke_rc"
    [ "$smoke_rc" = 0 ] && mark_done smoke
fi

if phase_done kernel_bench; then
    echo "$(ts) == kernel bench == (stamped, skipping)"
else
    gap
    echo "$(ts) == kernel bench (csv + dispatch prefs) =="
    # also uncapped: it is a TPU-attached client
    python tools/kernel_bench.py --csv "$ART/bench_kernels.csv" \
        --write-prefs > "$ART/bench_kernels.jsonl" 2>"$ART/bench_kernels.err"
    kb_rc=$?
    tail -3 "$ART/bench_kernels.jsonl"
    # kernel_bench exits 0 when it skips off-TPU (tunnel dropped between
    # phases): no TPU-labeled rows means the phase did NOT validate
    if ! grep -q '"backend": "tpu"' "$ART/bench_kernels.jsonl"; then
        echo "$(ts) kernel_bench: no TPU rows (backend fell back?); phase failed"
        kb_rc=1
    fi
    echo "$(ts) kernel_bench rc=$kb_rc"
    [ "$kb_rc" = 0 ] && mark_done kernel_bench
fi

if phase_done sweep_attn; then
    echo "$(ts) == attention geometry sweep == (stamped, skipping)"
else
    gap
    echo "$(ts) == attention geometry sweep =="
    python tools/kernel_bench.py --sweep-attn --csv "$ART/sweep_attn.csv" \
        > "$ART/sweep_attn.jsonl" 2>"$ART/sweep_attn.err"
    sw_rc=$?
    tail -3 "$ART/sweep_attn.jsonl"
    if ! grep -q '"backend": "tpu"' "$ART/sweep_attn.jsonl"; then
        echo "$(ts) sweep: no TPU rows; phase failed"
        sw_rc=1
    fi
    echo "$(ts) sweep rc=$sw_rc"
    [ "$sw_rc" = 0 ] && mark_done sweep_attn
fi

if phase_done bench; then
    echo "$(ts) == bench == (stamped, skipping)"
else
    gap
    echo "$(ts) == bench =="
    python bench.py > "$ART/bench_tpu.json" 2>"$ART/bench_tpu.err"
    cat "$ART/bench_tpu.json"
    # bench.py always exits 0 by design; judge the JSON instead
    bench_rc=$(ART="$ART" python - <<'EOF'
import json, os
try:
    out = json.load(open(os.path.join(os.environ["ART"],
                                      "bench_tpu.json")))
    ok = (out.get("backend") == "tpu" and float(out.get("value", 0)) > 0
          and not out.get("errors"))
    print(0 if ok else 1)
except Exception:
    print(1)
EOF
)
    echo "$(ts) bench rc=$bench_rc"
    [ "$bench_rc" = 0 ] && mark_done bench
fi

if phase_done trace; then
    echo "$(ts) == profiler trace == (stamped, skipping)"
else
    gap
    echo "$(ts) == profiler trace =="
    python tools/profile_step.py --outdir "$ART/trace" \
        > "$ART/trace_summary.txt" 2>"$ART/trace.err"
    tr_rc=$?
    tail -5 "$ART/trace_summary.txt"
    echo "$(ts) trace rc=$tr_rc"
    [ "$tr_rc" = 0 ] && mark_done trace
fi

echo "$(ts) == summary =="
for p in smoke kernel_bench sweep_attn bench trace; do
    if phase_done "$p"; then echo "  $p: PASS ($(cat "$ART/.phase_$p.ok"))";
    else echo "  $p: INCOMPLETE"; fi
done
echo "artifacts in $ART/: smoke_tpu.log bench_kernels.{csv,jsonl} sweep_attn.{csv,jsonl} bench_tpu.json trace/"
echo "next: review dispatch_prefs.json + commit artifacts"

for p in smoke kernel_bench sweep_attn bench trace; do
    phase_done "$p" || exit 1
done
exit 0
