"""Kernel autotuner: sweep the dispatch candidate spaces and persist
per-topology dispatch tables (+ restamped perf budgets).

The measurement substrate existed (benchlib amortized timing, the PR-8
device-event attribution, perf_budget provenance, the stale-table
RuntimeWarning contract); this is its consumer.  Per (op family, shape
class, dtype, topology) the sweep times:

- **routing**: each Pallas kernel family vs its XLA oracle
  (``prefer_pallas`` booleans, the VERDICT r2 #2 table);
- **attn_block_cap**: flash-attention sequence-block geometries per
  padded head dim (the kernel_bench --sweep-attn grid);
- **pipeline.max_bucket_bytes**: flat-pipeline bucket chunking for the
  comm/compute overlap schedule;
- **pipeline.reduce_decompose**: psum vs reduce-scatter+all-gather for
  the bucketed all-reduce.

Every timing uses benchlib's amortized on-device loop; a decision that
flips a design default must beat it beyond the session's measured
noise floor (``benchlib.noise_floor_pct``), and wall-clock winners are
cross-checked against device-event attribution
(``telemetry.profiler``): a winner whose edge disappears in the device
timeline is rejected as noise.  Results persist as ONE prefs table per
topology — ``apex_tpu/ops/dispatch_prefs.<topology>.json`` with
methodology + topology + noise-floor stamps — which
``ops/_dispatch.py`` selects by runtime topology (falling back to the
shipped default table with the loud-warning discipline).  The sweep
also restamps ``tools/perf_budget.json`` rows it can ground, so the
perf gate and the tuner share one source of truth.

    python tools/autotune.py --cpu-smoke [--out DIR]
        # deterministic plumbing run: tiny shapes, fixed candidate
        # lists, CPU interpret mode; writes the per-topology table and
        # a restamped budget COPY into --out (never the repo files),
        # then demonstrates the table changes >= 1 dispatch decision
    python tools/autotune.py --full
        # hardware sweep: full candidate spaces; installs
        # apex_tpu/ops/dispatch_prefs.<topology>.json and restamps
        # tools/perf_budget.json in place (refuses off-TPU)
    python tools/autotune.py --validate [FILES...]
        # stdlib-only schema check over shipped dispatch_prefs*.json
        # (tools/check.sh runs this: a hand-edited table fails fast
        # instead of being silently discarded at import)
"""

from __future__ import annotations

import argparse
import functools
import glob
import json
import os as _os
import re
import sys as _sys
import time

# runnable straight from a checkout with no install (tools/lint.py idiom)
_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _ROOT not in _sys.path:
    _sys.path.insert(0, _ROOT)

_TOOLS = _os.path.join(_ROOT, "tools")
DEFAULT_OUT = _os.path.join(_TOOLS, "artifacts", "autotune")
BUDGET_PATH = _os.path.join(_TOOLS, "perf_budget.json")

# keep in sync with apex_tpu.ops._dispatch.SCHEMA_VERSION (asserted by
# tests/test_autotune.py); duplicated so --validate stays jax-free.
SCHEMA_VERSION = 2

_REDUCE_CHOICES = ("psum", "reduce_scatter")

# keep in sync with apex_tpu.ops._dispatch.KV_DTYPE_CHOICES; duplicated
# so --validate stays jax-free.
_KV_DTYPE_CHOICES = ("f32", "bf16", "int8")
_WEIGHT_DTYPE_CHOICES = ("f32", "int8")


def _load_sibling(name):
    """Import a sibling tools/ module (tools/ is not a package)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, _os.path.join(_TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# ---------------------------------------------------------------------------
# schema validation (stdlib only — check.sh runs this on every push)
# ---------------------------------------------------------------------------

def validate_table(doc, *, per_topology: bool, path: str = "") -> list:
    """Schema errors for one dispatch-prefs doc (empty list = valid).

    The default ``dispatch_prefs.json`` (``per_topology=False``) needs
    the methodology stamp and in-domain values; a per-topology
    ``dispatch_prefs.<key>.json`` additionally needs the schema
    version, a topology block whose key matches the filename, and a
    noise-floor stamp — everything ``ops/_dispatch.py`` would silently
    discard the table for lacking must fail loudly here instead."""
    errs = []
    if not isinstance(doc, dict):
        return [f"{path or '<doc>'}: not a JSON object"]

    def err(msg):
        errs.append(f"{path or '<doc>'}: {msg}")

    if doc.get("methodology") != "amortized":
        err(f"methodology must be 'amortized', found "
            f"{doc.get('methodology')!r} (tables without the stamp "
            "measured the relay, not the kernels, and are ignored at "
            "import)")

    prefs = doc.get("prefer_pallas", {})
    if not isinstance(prefs, dict):
        err("prefer_pallas must be an object")
    else:
        for k, v in prefs.items():
            if not isinstance(v, bool):
                err(f"prefer_pallas[{k!r}] must be a JSON boolean, "
                    f"found {v!r}")

    caps = doc.get("attn_block_cap", {})
    if not isinstance(caps, dict):
        err("attn_block_cap must be an object")
    else:
        for k, v in caps.items():
            if not isinstance(v, int) or isinstance(v, bool) \
                    or v <= 0 or v % 128:
                err(f"attn_block_cap[{k!r}] must be a positive "
                    f"multiple of 128, found {v!r}")

    pipe = doc.get("pipeline", {})
    if not isinstance(pipe, dict):
        err("pipeline must be an object")
    else:
        if "max_bucket_bytes" in pipe:
            v = pipe["max_bucket_bytes"]
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v <= 0):
                err(f"pipeline.max_bucket_bytes must be a positive "
                    f"integer or null, found {v!r}")
        if "reduce_decompose" in pipe \
                and pipe["reduce_decompose"] not in _REDUCE_CHOICES:
            err(f"pipeline.reduce_decompose must be one of "
                f"{_REDUCE_CHOICES}, found {pipe['reduce_decompose']!r}")

    f8 = doc.get("fp8", {})
    if not isinstance(f8, dict):
        err("fp8 must be an object")
    else:
        for k in ("amax_history_len", "interval"):
            if k in f8 and (not isinstance(f8[k], int)
                            or isinstance(f8[k], bool) or f8[k] <= 0):
                err(f"fp8.{k} must be a positive integer, "
                    f"found {f8[k]!r}")

    quant = doc.get("quantization", {})
    if not isinstance(quant, dict):
        err("quantization must be an object")
    elif "int8_dynamic" in quant \
            and not isinstance(quant["int8_dynamic"], bool):
        err(f"quantization.int8_dynamic must be a JSON boolean, "
            f"found {quant['int8_dynamic']!r}")

    srv = doc.get("serving", {})
    if not isinstance(srv, dict):
        err("serving must be an object")
    else:
        for k in ("page_size", "decode_window", "prefill_batch"):
            if k in srv and (not isinstance(srv[k], int)
                             or isinstance(srv[k], bool)
                             or srv[k] <= 0):
                err(f"serving.{k} must be a positive integer, "
                    f"found {srv[k]!r}")
        # spec_k is the one serving integer where 0 is a VALID value
        # (speculation off), so it cannot ride the positive-int loop
        if "spec_k" in srv and (not isinstance(srv["spec_k"], int)
                                or isinstance(srv["spec_k"], bool)
                                or srv["spec_k"] < 0):
            err(f"serving.spec_k must be a non-negative integer, "
                f"found {srv['spec_k']!r}")
        if "kv_dtype" in srv and srv["kv_dtype"] not in _KV_DTYPE_CHOICES:
            err(f"serving.kv_dtype must be one of {_KV_DTYPE_CHOICES}, "
                f"found {srv['kv_dtype']!r}")
        if "weight_dtype" in srv \
                and srv["weight_dtype"] not in _WEIGHT_DTYPE_CHOICES:
            err(f"serving.weight_dtype must be one of "
                f"{_WEIGHT_DTYPE_CHOICES}, "
                f"found {srv['weight_dtype']!r}")
        if "prefix_share" in srv \
                and not isinstance(srv["prefix_share"], bool):
            err(f"serving.prefix_share must be a JSON boolean, "
                f"found {srv['prefix_share']!r}")

    topo = doc.get("topology")
    if topo is not None:
        if not isinstance(topo, dict) or not isinstance(
                topo.get("key"), str) or not topo.get("key"):
            err("topology block must be an object with a string 'key'")
        else:
            for field, typ in (("device_kind", str),
                               ("device_count", int)):
                if not isinstance(topo.get(field), typ) \
                        or isinstance(topo.get(field), bool):
                    err(f"topology.{field} must be a {typ.__name__}")

    if per_topology:
        if doc.get("schema") != SCHEMA_VERSION:
            err(f"per-topology tables require schema={SCHEMA_VERSION}, "
                f"found {doc.get('schema')!r}")
        if topo is None:
            err("per-topology tables require a topology block")
        elif isinstance(topo, dict) and isinstance(topo.get("key"), str) \
                and path:
            want = f"dispatch_prefs.{topo['key']}.json"
            if _os.path.basename(path) != want:
                err(f"filename must match topology.key "
                    f"(expected {want})")
        nf = doc.get("noise_floor_pct")
        if not isinstance(nf, (int, float)) or isinstance(nf, bool) \
                or nf < 0:
            err(f"noise_floor_pct must be a non-negative number, "
                f"found {nf!r}")
    return errs


LEDGER_PATH = _os.path.join(_ROOT, "apex_tpu", "lint", "cost",
                            "ledger.json")


def _ledger_schema():
    """The apexcost ledger schema validator, loaded from its module
    FILE so --validate stays jax-free (importing the apex_tpu.lint
    package would pull the whole lint stack; ledger.py itself is
    stdlib-only)."""
    import importlib.util
    p = _os.path.join(_ROOT, "apex_tpu", "lint", "cost", "ledger.py")
    spec = importlib.util.spec_from_file_location("_apexcost_ledger", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate_paths(paths=None) -> list:
    """Validate every shipped dispatch_prefs*.json plus the apexcost
    cost ledger (or the given paths); returns all errors.  Unreadable
    JSON is an error — a hand-edit that truncates a file must fail CI,
    not degrade to design defaults silently.  A path named
    ``ledger.json`` (or any doc carrying a ``cards`` map) validates
    against the apexcost ledger schema instead of the dispatch-table
    schema."""
    if not paths:
        paths = sorted(glob.glob(_os.path.join(
            _ROOT, "apex_tpu", "ops", "dispatch_prefs*.json")))
        paths.append(LEDGER_PATH)
    errs = []
    for p in paths:
        base = _os.path.basename(p)
        try:
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            errs.append(f"{p}: unreadable ({e})")
            continue
        if base == "ledger.json" or (isinstance(doc, dict)
                                     and "cards" in doc):
            errs.extend(_ledger_schema().validate(doc, p))
            continue
        per_topo = re.fullmatch(r"dispatch_prefs\..+\.json",
                                base) is not None
        errs.extend(validate_table(doc, per_topology=per_topo, path=p))
    return errs


# ---------------------------------------------------------------------------
# budget restamp (stdlib only)
# ---------------------------------------------------------------------------

def restamp_budget(budget: dict, measured: dict, *, topology: str,
                   backend: str, noise_floor_pct: float, mode: str,
                   when: str) -> list:
    """Restamp ``perf_budget.json`` rows the sweep grounded: for each
    measured metric present in the budget, the floor (or ceiling)
    moves to the measured value and the row gains sweep provenance, so
    the perf gate defends what the tuner just measured — one source of
    truth.  The top-level stamp date only moves on a HARDWARE sweep
    (perf_gate's auto-gating keys off it; a CPU smoke restamp is
    plumbing, not a perf claim).  Mutates ``budget``; returns the
    restamped row names."""
    rows = []
    metrics = budget.setdefault("metrics", {})
    for name, value in sorted(measured.items()):
        spec = metrics.get(name)
        if not isinstance(spec, dict) or not isinstance(
                value, (int, float)) or isinstance(value, bool):
            continue
        if spec.get("direction", "higher") == "higher":
            spec["floor"] = round(float(value), 3)
        else:
            spec["ceiling"] = round(float(value), 3)
        spec["restamped"] = {
            "by": "tools/autotune.py", "mode": mode,
            "topology": topology, "backend": backend,
            "measured": round(float(value), 4),
            "noise_floor_pct": round(float(noise_floor_pct), 2),
            "at": when}
        rows.append(name)
    if rows and backend == "tpu":
        budget["stamped_at"] = when
        budget["stamped_from"] = (f"tools/autotune.py sweep on "
                                  f"{topology} at {when}")
    return rows


# ---------------------------------------------------------------------------
# sweep machinery (jax imported lazily)
# ---------------------------------------------------------------------------

def smoke_config() -> dict:
    """Fixed tiny candidate spaces: the whole sweep -> table ->
    dispatch-decision-change -> budget-restamp pipeline runs
    deterministically in CPU interpret mode (tier-1), no hardware."""
    return {
        "mode": "cpu-smoke", "iters": 20, "reps": 3,
        "mt_n": 4096,
        "welford_shape": (256, 128),
        "attn_shapes": [(1, 1, 256, 64)],
        "attn_caps": [128, 256],
        "attn_grad": False,
        "chunk_candidates": [None, 16384],
        "pipe_layers": 4, "pipe_hidden": 32, "pipe_batch": 8,
        "reduce_n": 8192,
        "accum": dict(layers=3, hidden=32, batch=8, n_micro=(8,),
                      iters=2, reps=2),
        "fp8_hist_candidates": [4, 16],
        "fp8_interval_candidates": [1, 4],
        "fp8_layers": 4, "fp8_hidden": 32, "fp8_batch": 8,
        "int8_mkn": (64, 64, 64),
        "serving_page_candidates": [4, 8],
        "serving_window_candidates": [4, 8],
        "serving_layers": 2, "serving_hidden": 32,
        "serving_heads": 2, "serving_slots": 2, "serving_ctx": 16,
        # the kv-dtype leg pins head_dim=64 (hidden/heads): the bytes
        # ratio is structural in head_dim and the budget ceiling (0.55)
        # is stamped at the production width, not the smoke width
        "serving_quant_hidden": 256, "serving_quant_heads": 4,
        "serving_share_requests": 4,
        # one non-zero K: the smoke proves the sweep plumbing + the
        # bit-exact oracle; the K frontier itself is a --full question
        "serving_spec_candidates": [0, 2],
        "serving_prefill_batch": 2,
        "device_check_families": ["multi_tensor"],
    }


def full_config() -> dict:
    """Hardware candidate spaces (the overdue re-measure: run this in
    the first live TPU window — it restamps everything that predates
    the flat pipeline and the overlap schedule)."""
    return {
        "mode": "full", "iters": 10, "reps": 3,
        "mt_n": 1 << 24,
        "welford_shape": (64 * 56 * 56, 256),
        "attn_shapes": [(8, 16, 512, 64), (4, 16, 2048, 128),
                        (2, 16, 2048, 256)],
        "attn_caps": [128, 256, 512, 1024],
        "attn_grad": True,
        "chunk_candidates": [None, 1 << 25, 1 << 26, 1 << 27],
        "pipe_layers": 48, "pipe_hidden": 256, "pipe_batch": 64,
        "reduce_n": 1 << 22,
        "accum": dict(layers=16, hidden=128, batch=32, n_micro=(8,),
                      iters=5, reps=3),
        "fp8_hist_candidates": [4, 16, 64],
        "fp8_interval_candidates": [1, 4, 16],
        "fp8_layers": 24, "fp8_hidden": 512, "fp8_batch": 64,
        "int8_mkn": (4096, 4096, 4096),
        "serving_page_candidates": [8, 16, 32, 64],
        "serving_window_candidates": [8, 16, 32],
        "serving_layers": 8, "serving_hidden": 512,
        "serving_heads": 8, "serving_slots": 16, "serving_ctx": 1024,
        "serving_quant_hidden": 512, "serving_quant_heads": 8,
        "serving_share_requests": 8,
        "serving_spec_candidates": [0, 2, 4, 8],
        "serving_prefill_batch": 4,
        "device_check_families": ["multi_tensor", "welford",
                                  "layer_norm", "pipeline", "fp8"],
    }


def _time(fn, *args, cfg):
    import jax

    from apex_tpu.benchlib import timeit
    return timeit(jax.jit(fn), *args, iters=cfg["iters"],
                  reps=cfg["reps"], adaptive=(cfg["mode"] == "full"))


def measure_noise_floor(cfg) -> float:
    """Session noise floor from a representative fused body (the
    welford oracle at this config's shape)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.benchlib import noise_floor_pct
    from apex_tpu.ops import welford as wf
    r, c = cfg["welford_shape"]
    x = jax.random.normal(jax.random.key(11), (r, c), jnp.bfloat16)
    return round(noise_floor_pct(
        jax.jit(wf.welford_mean_var_ref), x,
        trials=3, iters=cfg["iters"], reps=cfg["reps"]), 2)


def device_event_check(label: str, fast, slow, outdir: str) -> dict:
    """Cross-check a wall-clock verdict against the device timeline:
    capture the winner and the loser under short profiler windows and
    compare device-busy time (compute+collective+transfer, interval-
    union).  ``fast``/``slow`` are (callable, args) with the
    wall-clock winner first.  Verdict "rejected" means the wall-clock
    edge disappeared on device — the decision must not flip a default
    on it."""
    import jax

    from apex_tpu.benchlib import sync
    from apex_tpu.telemetry.profiler import attribution, capture, events
    busy, n_events = {}, {}
    for side, (fn, args) in (("fast", fast), ("slow", slow)):
        d = _os.path.join(outdir, "device_check",
                          re.sub(r"[^A-Za-z0-9_.-]", "_",
                                 f"{label}_{side}"))
        _os.makedirs(d, exist_ok=True)
        try:
            # two sides = two programs by design (one jit each, not a
            # per-iteration retrace: the capture loop reuses jf)
            # apexlint: disable-next=APX302
            jf = jax.jit(fn)
            out = jf(*args)
            sync(out)                 # compile OUTSIDE the window
            with capture.trace(d):
                for _ in range(3):
                    out = jf(*args)
                sync(out)
            evs = events.load_device_events(d)
        except Exception as e:       # a failed capture must not kill
            return {"checked": False,  # the sweep — record and move on
                    "reason": f"capture failed: {e!r}"[:200]}
        b = attribution.attribute(evs)
        busy[side] = round(b.compute_ms + b.collective_ms
                           + b.transfer_ms, 4)
        n_events[side] = b.n_events
    if not n_events["fast"] or not n_events["slow"]:
        return {"checked": False, "reason": "no device events parsed",
                "n_events": n_events}
    verdict = "confirmed" if busy["fast"] < busy["slow"] else "rejected"
    return {"checked": True, "verdict": verdict,
            "fast_busy_ms": busy["fast"], "slow_busy_ms": busy["slow"],
            "n_events": n_events}


def _routing_cases(cfg):
    """(family, shape_desc, dtype, kernel_fn, oracle_fn, args) per
    measured shape class.  Smoke keeps the two cheapest families; full
    covers every family kernel_bench maps (tools/kernel_bench.py
    _OP_FAMILY)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops import multi_tensor as mt
    from apex_tpu.ops import welford as wf
    cases = []
    key = jax.random.key(0)

    n = cfg["mt_n"]
    p = jax.random.normal(key, (n,), jnp.float32)
    g = jax.random.normal(jax.random.key(2), (n,), jnp.float32) * 0.01
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.01, step=3, adam_w_mode=True)
    cases.append(("multi_tensor", f"flat_adam/n={n}", "f32",
                  functools.partial(mt.flat_adam, **kw),
                  functools.partial(mt.flat_adam_ref, **kw),
                  (p, g, m, v)))

    r, c = cfg["welford_shape"]
    xw = jax.random.normal(key, (r, c), jnp.bfloat16)
    cases.append(("welford", f"{r}x{c}", "bf16",
                  wf.welford_mean_var, wf.welford_mean_var_ref, (xw,)))

    if cfg["mode"] == "full":
        from apex_tpu.ops import attention as attn
        from apex_tpu.ops import layer_norm as ln
        from apex_tpu.ops import softmax as sm
        from apex_tpu.ops import xentropy as xe

        def grad_of(f, n_args):
            return jax.grad(
                lambda *a: jnp.sum(f(*a).astype(jnp.float32) ** 2),
                argnums=tuple(range(n_args)))

        for (b, h, s, d) in cfg["attn_shapes"][:2]:
            ks = jax.random.split(key, 3)
            q, k, v_ = (jax.random.normal(kk, (b, h, s, d),
                                          jnp.bfloat16) for kk in ks)
            f_k = functools.partial(attn.flash_attention, causal=True)
            f_o = functools.partial(attn.attention_ref, causal=True)
            cases.append(("attention", f"b{b}h{h}s{s}d{d}", "bf16",
                          grad_of(f_k, 3), grad_of(f_o, 3),
                          (q, k, v_)))
        qf, kf, vf = (jax.random.normal(kk, (8, 16, 512, 64),
                                        jnp.float32)
                      for kk in jax.random.split(jax.random.key(5), 3))
        cases.append(("attention_f32", "b8h16s512d64", "f32",
                      grad_of(functools.partial(attn.flash_attention,
                                                causal=True), 3),
                      grad_of(functools.partial(attn.attention_ref,
                                                causal=True), 3),
                      (qf, kf, vf)))
        for (r_, hdim) in [(8192, 1024), (4096, 4096)]:
            x = jax.random.normal(key, (r_, hdim), jnp.bfloat16)
            w = jnp.ones((hdim,), jnp.bfloat16)
            b_ = jnp.zeros((hdim,), jnp.bfloat16)
            cases.append(("layer_norm", f"{r_}x{hdim}", "bf16",
                          ln.fused_layer_norm, ln.layer_norm_ref,
                          (x, w, b_)))
        xs = jax.random.normal(key, (8 * 16, 512, 512), jnp.bfloat16)
        cases.append(("softmax", "128x512x512", "bf16",
                      functools.partial(
                          sm.scaled_upper_triang_masked_softmax,
                          scale=1.0),
                      functools.partial(
                          sm.scaled_upper_triang_masked_softmax_ref,
                          scale=1.0), (xs,)))
        logits = jax.random.normal(key, (4096, 32768), jnp.bfloat16)
        labels = jax.random.randint(jax.random.key(1), (4096,), 0,
                                    32768)
        cases.append(("xentropy", "4096x32768", "bf16",
                      lambda l: xe.softmax_cross_entropy(l, labels),
                      lambda l: xe.softmax_cross_entropy_ref(l, labels),
                      (logits,)))
    return cases


def sweep_routing(cfg, noise_pct: float, outdir: str) -> list:
    """Pallas-vs-XLA-oracle routing per family × shape class.  A
    family flips to the XLA path only when some shape lost beyond the
    noise floor AND (where a device check ran) the edge survives in
    the device timeline."""
    records = []
    by_family = {}
    for fam, shape, dtype, kern, oracle, args in _routing_cases(cfg):
        k_ms = _time(kern, *args, cfg=cfg)
        o_ms = _time(oracle, *args, cfg=cfg)
        rec = {"space": "routing", "family": fam, "shape": shape,
               "dtype": dtype, "kernel_ms": round(k_ms, 4),
               "oracle_ms": round(o_ms, 4),
               "speedup": round(o_ms / k_ms, 3) if k_ms else None,
               "noise_floor_pct": noise_pct}
        records.append(rec)
        by_family.setdefault(fam, []).append(
            (rec, kern, oracle, args))

    for fam, shapes in by_family.items():
        sps = [r["speedup"] for r, *_ in shapes
               if r["speedup"] is not None]
        lost = [x for x in sps if x < 1.0 - noise_pct / 100.0]
        prefer = not lost
        if lost and fam in cfg["device_check_families"]:
            # cross-check the WORST shape's verdict on the device
            # timeline before routing the whole family off Pallas
            worst = min(shapes, key=lambda s: s[0]["speedup"] or 1.0)
            rec, kern, oracle, args = worst
            check = device_event_check(
                f"routing_{fam}", fast=(oracle, args),
                slow=(kern, args), outdir=outdir)
            rec["device_check"] = check
            if check.get("checked") and check["verdict"] == "rejected":
                prefer = True
                rec["rejected_as_noise"] = True
        for rec, *_ in shapes:
            rec["decision"] = {"prefer_pallas": {fam: prefer}}
    return records


def sweep_attn_caps(cfg, noise_pct: float) -> list:
    """Flash-attention sequence-block-cap sweep (the kernel_bench
    --sweep-attn grid through the same amortized timer); winner per
    padded head dim via kernel_bench.select_attn_caps (a cap must be
    measured on EVERY swept shape of its dp to win)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops import attention as attn
    kb = _load_sibling("kernel_bench")
    records = []
    sweep_times = {}
    for (b, h, s, d) in cfg["attn_shapes"]:
        ks = jax.random.split(jax.random.key(7), 3)
        q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
                   for kk in ks)
        dp = attn._round_up(d, attn._LANES)
        if cfg["attn_grad"]:
            fn = jax.grad(
                lambda q, k, v: jnp.sum(attn.flash_attention(
                    q, k, v, causal=True).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))
        else:
            fn = functools.partial(attn.flash_attention, causal=True)
        shape_ms = {}
        # save/restore an operator's own cap override (the pop-only
        # shape would delete it for the rest of the process)
        prev_cap = _os.environ.get("APEX_TPU_ATTN_BLOCK_CAP")
        for cap in cfg["attn_caps"]:
            if (cap > attn._round_up(s, attn._LANES)
                    or cap > attn._sweep_cap_ceiling(dp)):
                continue
            _os.environ["APEX_TPU_ATTN_BLOCK_CAP"] = str(cap)
            try:
                # re-jit per cap ON PURPOSE: the env knob changes
                # kernel geometry (apexlint: disable-next=APX302)
                ms = _time(fn, q, k, v, cfg=cfg)
            except Exception as e:
                records.append({"space": "attn_block_cap",
                                "family": "attention",
                                "shape": f"b{b}h{h}s{s}d{d}",
                                "cap": cap, "error": repr(e)[:200]})
                continue
            finally:
                if prev_cap is None:
                    _os.environ.pop("APEX_TPU_ATTN_BLOCK_CAP", None)
                else:
                    _os.environ["APEX_TPU_ATTN_BLOCK_CAP"] = prev_cap
            shape_ms[cap] = ms
        if not shape_ms:
            continue
        best = min(shape_ms.values())
        for cap, ms in shape_ms.items():
            sweep_times.setdefault((dp, cap), []).append(ms / best)
        records.append({"space": "attn_block_cap",
                        "family": "attention",
                        "shape": f"b{b}h{h}s{s}d{d}", "dtype": "bf16",
                        "dp": dp, "noise_floor_pct": noise_pct,
                        "candidates_ms": {str(c): round(m, 4)
                                          for c, m in shape_ms.items()}})
    caps = kb.select_attn_caps(sweep_times)
    if caps:
        records.append({"space": "attn_block_cap", "family": "attention",
                        "decision": {"attn_block_cap": caps}})
    return records


def sweep_pipeline_chunk(cfg, noise_pct: float, outdir: str) -> list:
    """``max_bucket_bytes`` candidates through a full flat-AMP train
    step (pack → unscale/norm → fused optimizer) on a many-leaf tree;
    the monolithic plan (None) is the design default and a chunked
    winner must beat it beyond the noise floor."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.bucketing_bench import (many_leaf_loss,
                                                     many_leaf_params)
    params = many_leaf_params(jax, jnp, cfg["pipe_layers"],
                              cfg["pipe_hidden"])
    x = jax.random.normal(jax.random.key(1),
                          (cfg["pipe_batch"], cfg["pipe_hidden"]))
    scaler = amp.LossScaleState.create(2.0 ** 12)
    # the SAME toy model bench_grad_accum measures (and the budget row
    # this sweep restamps) — see bucketing_bench.many_leaf_loss
    loss_fn = many_leaf_loss(jnp)

    times, steps = {}, {}
    for mbb in cfg["chunk_candidates"]:
        opt = FusedAdam(params, lr=1e-3, max_bucket_bytes=mbb)
        pipe = amp.FlatGradPipeline(optimizer=opt)
        hypers = {k: jnp.asarray(v, jnp.float32)
                  for k, v in opt.hypers.items()
                  if isinstance(v, float)}

        def step(work, opt_state, x, s, pipe=pipe, opt=opt,
                 hypers=hypers):
            loss, flat = pipe.scaled_value_and_grad(
                loss_fn, scaler, pipe.plan.unpack(work), x)
            new_w, _, new_s = opt._full_step_flat(
                work, None, opt_state, flat.bufs, s, 1.0, hypers,
                flat.found_inf)
            return loss, new_w, new_s

        # each candidate is its own bucket layout, so its own program
        # by design (apexlint: disable-next=APX302)
        times[mbb] = _time(step, opt._param_bufs, opt.opt_state, x,
                           jnp.int32(2), cfg=cfg)
        steps[mbb] = (step, (opt._param_bufs, opt.opt_state, x,
                             jnp.int32(2)))

    default_ms = times[None] if None in times else None
    winner = min(times, key=times.get)
    rec = {"space": "pipeline.max_bucket_bytes", "family": "pipeline",
           "shape": f"{cfg['pipe_layers']}layers"
                    f"x{cfg['pipe_hidden']}", "dtype": "f32",
           "noise_floor_pct": noise_pct,
           "candidates_ms": {str(k): round(v, 4)
                             for k, v in times.items()}}
    if winner is not None and default_ms is not None \
            and times[winner] < default_ms * (1.0 - noise_pct / 100.0):
        if "pipeline" in cfg["device_check_families"]:
            check = device_event_check(
                "pipeline_chunk", fast=steps[winner],
                slow=steps[None], outdir=outdir)
            rec["device_check"] = check
            if check.get("checked") and check["verdict"] == "rejected":
                rec["rejected_as_noise"] = True
                return [rec]
        rec["decision"] = {"pipeline": {"max_bucket_bytes": winner}}
    return [rec]


def sweep_reduce_decompose(cfg, noise_pct: float) -> list:
    """psum vs reduce-scatter+all-gather for the bucketed all-reduce,
    timed under shard_map over every local device; psum is the design
    default and reduce_scatter must win beyond the noise floor."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu import comm
    from apex_tpu.parallel.distributed import all_reduce_flat_buffers
    comm.destroy()
    mesh = comm.initialize(data=jax.device_count())
    try:
        buf = jax.random.normal(jax.random.key(3), (cfg["reduce_n"],),
                                jnp.float32)
        times = {}
        for dec in _REDUCE_CHOICES:
            def f(b, dec=dec):
                return all_reduce_flat_buffers(
                    [b], comm.AXIS_DATA, decompose=dec)[0]
            # the two decompositions are two programs by design
            # (apexlint: disable-next=APX302)
            fn = comm.shard_map(f, mesh, in_specs=(P(),), out_specs=P())
            times[dec] = _time(fn, buf, cfg=cfg)
    finally:
        comm.destroy()
    rec = {"space": "pipeline.reduce_decompose", "family": "pipeline",
           "shape": f"n={cfg['reduce_n']}/dev{jax.device_count()}",
           "dtype": "f32", "noise_floor_pct": noise_pct,
           "candidates_ms": {k: round(v, 4) for k, v in times.items()}}
    if times["reduce_scatter"] < times["psum"] * (1.0
                                                  - noise_pct / 100.0):
        rec["decision"] = {"pipeline":
                           {"reduce_decompose": "reduce_scatter"}}
    return [rec]


def sweep_fp8_cadence(cfg, noise_pct: float, outdir: str) -> list:
    """fp8 scaling-cadence sweep (amax history length x scale-update
    interval) through a full fp8 flat-AMP train step — fp8_matmul
    forward, packed grad-side scale update, fused optimizer with fp8
    weight slots.  The Fp8Policy defaults are the design default; a
    candidate cadence must beat them beyond the noise floor (and,
    where enabled, survive the device-timeline cross-check) before
    the table steers ``amp.fp8.tuned_policy()``."""
    import itertools

    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.amp import fp8 as fp8_mod
    from apex_tpu.fused_dense import fp8_matmul
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.bucketing_bench import many_leaf_params
    params = many_leaf_params(jax, jnp, cfg["fp8_layers"],
                              cfg["fp8_hidden"])
    x = jax.random.normal(jax.random.key(9),
                          (cfg["fp8_batch"], cfg["fp8_hidden"]))
    scaler = amp.LossScaleState.create(2.0 ** 12)
    default = (fp8_mod.Fp8Policy.amax_history_len,
               fp8_mod.Fp8Policy.interval)
    cands = sorted(set(itertools.product(
        cfg["fp8_hist_candidates"], cfg["fp8_interval_candidates"])
        ) | {default})

    times, steps = {}, {}
    for hist, interval in cands:
        policy = fp8_mod.Fp8Policy(amax_history_len=hist,
                                   interval=interval)
        opt = FusedAdam(params, lr=1e-3)
        opt.enable_fp8(policy)
        pipe = amp.FlatGradPipeline(optimizer=opt, fp8=policy)
        f8 = pipe.fp8_init()
        hypers = {k: jnp.asarray(v, jnp.float32)
                  for k, v in opt.hypers.items()
                  if isinstance(v, float)}

        def loss_fn(p, scales, x, policy=policy):
            h = x
            for k in sorted(p):
                h = jnp.tanh(fp8_matmul(h, p[k]["w"], policy=policy,
                                        w_scale=scales[k]["w"])
                             + p[k]["b"]) * p[k]["scale"] \
                    + p[k]["shift"]
            return jnp.mean(h ** 2)

        def step(work, opt_state, f8, x, s, pipe=pipe, opt=opt,
                 hypers=hypers, loss_fn=loss_fn):
            scales = opt.fp8_scales(opt_state)
            loss, flat, new_f8 = pipe.scaled_value_and_grad(
                loss_fn, scaler, pipe.plan.unpack(work), scales, x,
                fp8_state=f8)
            new_w, _, new_s = opt._full_step_flat(
                work, None, opt_state, flat.bufs, s, 1.0, hypers,
                flat.found_inf)
            return loss, new_w, new_s, new_f8

        # each cadence is its own program (history shapes differ) by
        # design (apexlint: disable-next=APX302)
        times[(hist, interval)] = _time(
            step, opt._param_bufs, opt.opt_state, f8, x,
            jnp.int32(2), cfg=cfg)
        steps[(hist, interval)] = (step, (opt._param_bufs,
                                          opt.opt_state, f8, x,
                                          jnp.int32(2)))

    winner = min(times, key=times.get)
    rec = {"space": "fp8.cadence", "family": "fp8",
           "shape": f"{cfg['fp8_layers']}layers"
                    f"x{cfg['fp8_hidden']}", "dtype": "e4m3/e5m2",
           "noise_floor_pct": noise_pct,
           "candidates_ms": {f"H{h}/N{n}": round(v, 4)
                             for (h, n), v in times.items()}}
    if winner != default and times[winner] \
            < times[default] * (1.0 - noise_pct / 100.0):
        if "fp8" in cfg["device_check_families"]:
            check = device_event_check(
                "fp8_cadence", fast=steps[winner],
                slow=steps[default], outdir=outdir)
            rec["device_check"] = check
            if check.get("checked") and check["verdict"] == "rejected":
                rec["rejected_as_noise"] = True
                return [rec]
        rec["decision"] = {"fp8": {"amax_history_len": winner[0],
                                   "interval": winner[1]}}
    return [rec]


def sweep_quantization(cfg, noise_pct: float) -> list:
    """int8 inference routing: dynamic full-int8 vs weight-only at one
    GEMM shape.  Weight-only is the design default (activation
    precision untouched); dynamic steers ``int8_matmul(dynamic=None)``
    only when it wins beyond the noise floor on THIS topology."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.quantization import int8_matmul, quantize_int8
    m, k, n = cfg["int8_mkn"]
    x = jax.random.normal(jax.random.key(12), (m, k), jnp.bfloat16)
    wq = quantize_int8(jax.random.normal(jax.random.key(13),
                                         (k, n)) * 0.05)
    times = {}
    for mode, dyn in (("weight_only", False), ("dynamic", True)):
        # the two modes are two programs by design
        # (apexlint: disable-next=APX302)
        times[mode] = _time(
            lambda x, dyn=dyn: int8_matmul(x, wq, dynamic=dyn), x,
            cfg=cfg)
    rec = {"space": "quantization.int8_dynamic", "family":
           "quantization", "shape": f"{m}x{k}x{n}", "dtype": "int8",
           "noise_floor_pct": noise_pct,
           "candidates_ms": {k_: round(v, 4)
                             for k_, v in times.items()}}
    if times["dynamic"] < times["weight_only"] * (1.0
                                                  - noise_pct / 100.0):
        rec["decision"] = {"quantization": {"int8_dynamic": True}}
    return [rec]


def sweep_serving_geometry(cfg, noise_pct: float) -> list:
    """Serving decode shape-bucket geometry: (page_size x decode
    window) through one compiled decode window at mid-generation
    occupancy, normalized to ms per emitted token (a bigger window
    amortizes dispatch but holds admission longer — the sweep only
    weighs device cost; the engine's latency SLO stays a caller
    knob).  (8, 8) is the design default; a candidate must beat it
    beyond the noise floor before the table steers
    ``serving.Engine``'s defaults via ``_dispatch.serving_pref``."""
    import itertools

    import jax

    from apex_tpu.serving.bench import bench_decode_step

    default = (8, 8)
    cands = sorted(set(itertools.product(
        cfg["serving_page_candidates"],
        cfg["serving_window_candidates"])) | {default})
    times = {}
    for page, window in cands:
        r = bench_decode_step(
            n_layers=cfg["serving_layers"],
            hidden=cfg["serving_hidden"],
            n_heads=cfg["serving_heads"],
            max_slots=cfg["serving_slots"], page_size=page,
            pages_per_slot=max(1, cfg["serving_ctx"] // page),
            window=window, iters=cfg["iters"], reps=cfg["reps"])
        times[(page, window)] = (r["decode_step_paged_ms"]
                                 / (cfg["serving_slots"] * window))
    winner = min(times, key=times.get)
    rec = {"space": "serving.decode_geometry", "family": "serving",
           "shape": f"b{cfg['serving_slots']}ctx{cfg['serving_ctx']}"
                    f"x{cfg['serving_layers']}L",
           "dtype": "f32", "noise_floor_pct": noise_pct,
           "candidates_ms_per_token": {
               f"p{p}/w{w}": round(v, 5)
               for (p, w), v in sorted(times.items())}}
    if winner != default and times[winner] \
            < times[default] * (1.0 - noise_pct / 100.0):
        rec["decision"] = {"serving": {"page_size": winner[0],
                                       "decode_window": winner[1]}}
    return [rec]


_SERVING_MEMORY_MEMO = {}


def _serving_memory_benches(cfg):
    """Run (once per config) the two serving-memory benches that both
    the sweep and the budget restamp consume — each builds and
    compiles its own engine, so re-running them for the budget rows
    would double the sweep's compile bill for identical numbers."""
    from apex_tpu.serving.bench import bench_kv_quant_gather, \
        bench_prefix_admission
    key = (cfg["serving_layers"], cfg["serving_quant_hidden"],
           cfg["serving_quant_heads"], cfg["serving_slots"],
           cfg["serving_hidden"], cfg["serving_heads"],
           cfg["serving_share_requests"], cfg["iters"], cfg["reps"])
    if key not in _SERVING_MEMORY_MEMO:
        rq = bench_kv_quant_gather(
            n_layers=cfg["serving_layers"],
            hidden=cfg["serving_quant_hidden"],
            n_heads=cfg["serving_quant_heads"],
            max_slots=cfg["serving_slots"], page_size=8,
            pages_per_slot=2, iters=cfg["iters"], reps=cfg["reps"])
        rp = bench_prefix_admission(
            n_requests=cfg["serving_share_requests"],
            n_layers=cfg["serving_layers"],
            hidden=cfg["serving_hidden"],
            n_heads=cfg["serving_heads"], page_size=4,
            pages_per_slot=8, prompt_len=12, window=4)
        _SERVING_MEMORY_MEMO[key] = (rq, rp)
    return _SERVING_MEMORY_MEMO[key]


def sweep_serving_memory(cfg, noise_pct: float) -> list:
    """Serving memory frontier: kv_dtype and prefix_share.

    kv_dtype weighs the int8 gather+dequantize leg against the bf16
    gather (bench_kv_quant_gather) — the bytes halving is structural,
    so int8 wins unless its cast overhead exceeds the noise floor (the
    memory is free; only the compute tax can disqualify it).
    prefix_share is graded structurally: an N-way shared-prompt serve
    (bench_prefix_admission) must show prefill savings at or above the
    budget floor (2.0) with every request completed — wall clock never
    decides, the engine's prefill/extend counters do."""
    rq, rp = _serving_memory_benches(cfg)
    rec_q = {"space": "serving.kv_dtype", "family": "serving",
             "shape": f"b{rq['kv_gather_slots']}"
                      f"ctx{rq['kv_gather_ctx']}"
                      f"d{rq['kv_gather_head_dim']}",
             "dtype": "int8", "noise_floor_pct": noise_pct,
             "candidates_ms": {
                 "bf16": rq["kv_quant_gather_bf16_ms"],
                 "int8": rq["kv_quant_gather_int8_ms"]},
             "kv_bytes_per_token_ratio": rq["kv_bytes_per_token_ratio"]}
    if rq["kv_quant_gather_int8_ms"] <= \
            rq["kv_quant_gather_bf16_ms"] * (1.0 + noise_pct / 100.0):
        rec_q["decision"] = {"serving": {"kv_dtype": "int8"}}

    n_req = cfg["serving_share_requests"]
    rec_p = {"space": "serving.prefix_share", "family": "serving",
             "shape": f"n{n_req}p{rp['prefix_prompt_len']}",
             "dtype": "f32", "noise_floor_pct": noise_pct,
             "candidates_ms": {
                 "shared": rp["prefix_admission_ms"]},
             "prefix_prefill_savings": rp["prefix_prefill_savings"],
             "prefix_completed": rp["prefix_completed"]}
    if rp["prefix_prefill_savings"] >= 2.0 \
            and rp["prefix_completed"] == n_req:
        rec_p["decision"] = {"serving": {"prefix_share": True}}
    return [rec_q, rec_p]


_SERVING_COMPUTE_MEMO = {}


def _serving_compute_benches(cfg):
    """Run (once per config) the speculative-decode and batched-
    prefill benches that both the compute sweep and the budget
    restamp consume — each builds and AOT-compiles engines, the most
    expensive fixtures in the sweep."""
    from apex_tpu.serving.bench import bench_batched_prefill, \
        bench_spec_decode
    key = (cfg["serving_layers"], cfg["serving_hidden"],
           cfg["serving_heads"],
           tuple(cfg["serving_spec_candidates"]),
           cfg["serving_prefill_batch"])
    if key not in _SERVING_COMPUTE_MEMO:
        spec_runs = {}
        for k in cfg["serving_spec_candidates"]:
            if k == 0:
                continue    # the K=0 leg rides every spec run
            spec_runs[k] = bench_spec_decode(
                n_requests=cfg["serving_slots"],
                n_layers=cfg["serving_layers"],
                hidden=cfg["serving_hidden"],
                n_heads=cfg["serving_heads"], spec_k=k)
        rb = bench_batched_prefill(
            n_requests=cfg["serving_prefill_batch"],
            n_layers=cfg["serving_layers"],
            hidden=cfg["serving_hidden"],
            n_heads=cfg["serving_heads"],
            prefill_batch=cfg["serving_prefill_batch"])
        _SERVING_COMPUTE_MEMO[key] = (spec_runs, rb)
    return _SERVING_COMPUTE_MEMO[key]


def sweep_serving_compute(cfg, noise_pct: float) -> list:
    """Serving compute frontier: spec_k, weight_dtype and
    prefill_batch.

    spec_k weighs each candidate K's speculative window wall-clock
    against the plain window on the repetitive-suffix fixture — a K
    only becomes the table's decision when it beats K=0 beyond the
    noise floor AND its greedy stream stayed bit-exact (the free
    oracle; a K that ever diverges is a bug, not a slow candidate).
    weight_dtype times the decode window with int8-quantized matmul
    weights against f32 — the HBM halving is structural, so int8 wins
    unless its dequant tax exceeds the noise floor (the kv_dtype
    rule, applied to the weight planes).  prefill_batch is graded
    structurally from program-invocation counters: B requests must
    drain through ONE call with the serial stream reproduced
    bit-exactly."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import serving
    from apex_tpu.benchlib import timeit
    from apex_tpu.serving.bench import _tiny_setup

    spec_runs, rb = _serving_compute_benches(cfg)

    # --- serving.spec_k -------------------------------------------------
    cands_ms = {"k0": None}
    exact = True
    for k, r in sorted(spec_runs.items()):
        cands_ms[f"k{k}"] = r["spec_verify_step_ms"]
        if cands_ms["k0"] is None:
            cands_ms["k0"] = r["spec_plain_window_ms"]
        exact = exact and bool(r["spec_bit_exact"])
    rec_k = {"space": "serving.spec_k", "family": "serving",
             "shape": f"L{cfg['serving_layers']}"
                      f"h{cfg['serving_hidden']}",
             "dtype": "f32", "noise_floor_pct": noise_pct,
             "candidates_ms": cands_ms,
             "spec_accept_rates": {
                 f"k{k}": r["spec_accept_rate"]
                 for k, r in sorted(spec_runs.items())},
             "spec_bit_exact": int(exact)}
    timed = {k: r["spec_verify_step_ms"]
             for k, r in spec_runs.items()}
    if timed and exact:
        best = min(timed, key=timed.get)
        if timed[best] < cands_ms["k0"] * (1.0 - noise_pct / 100.0):
            rec_k["decision"] = {"serving": {"spec_k": best}}

    # --- serving.weight_dtype -------------------------------------------
    cfg2, params, spec2, state = _tiny_setup(
        jax, jnp, cfg["serving_layers"], cfg["serving_hidden"],
        cfg["serving_heads"], cfg["serving_slots"], 8,
        max(1, cfg["serving_ctx"] // 8), 8)
    win = serving.decode_window_fn(cfg2, spec2, 8)
    times = {}
    for wd in ("f32", "int8"):
        wp = serving.quantize_serving_params(params, wd)
        # one program per weight dtype by design
        # apexlint: disable-next=APX302
        times[wd] = timeit(jax.jit(win), wp, state,
                           iters=cfg["iters"], reps=cfg["reps"])
    rec_w = {"space": "serving.weight_dtype", "family": "serving",
             "shape": f"L{cfg['serving_layers']}"
                      f"h{cfg['serving_hidden']}",
             "dtype": "int8", "noise_floor_pct": noise_pct,
             "candidates_ms": {k: round(v, 4)
                               for k, v in times.items()}}
    if times["int8"] <= times["f32"] * (1.0 + noise_pct / 100.0):
        rec_w["decision"] = {"serving": {"weight_dtype": "int8"}}

    # --- serving.prefill_batch ------------------------------------------
    b = cfg["serving_prefill_batch"]
    rec_b = {"space": "serving.prefill_batch", "family": "serving",
             "shape": f"b{b}", "dtype": "f32",
             "noise_floor_pct": noise_pct,
             "candidates_ms": {
                 "batched": rb["batched_prefill_ms"],
                 "serial": rb["serial_prefill_ms"]},
             "batched_prefill_speedup": rb["batched_prefill_speedup"],
             "batched_prefill_bit_exact":
                 rb["batched_prefill_bit_exact"]}
    if rb["batched_prefill_speedup"] >= 1.5 \
            and rb["batched_prefill_bit_exact"]:
        rec_b["decision"] = {"serving": {"prefill_batch": b}}
    return [rec_k, rec_w, rec_b]


def measure_budget_rows(cfg) -> dict:
    """Sweep measurements that ground perf_budget rows (dotted metric
    path -> value).  grad_accum_n8_speedup comes from the same flat-vs-
    per-leaf accumulation legs bench.py reports, at this config's
    scale; the serving rows come from the same end-to-end engine
    bench.py's serving extra runs — autotune --full is the designated
    restamp vehicle for both (they grade no-data until then)."""
    from apex_tpu.optimizers.bucketing_bench import bench_grad_accum
    from apex_tpu.serving.bench import bench_serving
    r = bench_grad_accum(**cfg["accum"])
    out = {}
    if "grad_accum_n8_speedup" in r:
        out["extra.grad_accum_n8_speedup"] = r["grad_accum_n8_speedup"]
    s = bench_serving(
        n_requests=2 * cfg["serving_slots"],
        n_layers=cfg["serving_layers"], hidden=cfg["serving_hidden"],
        n_heads=cfg["serving_heads"], max_slots=cfg["serving_slots"],
        page_size=8, pages_per_slot=max(1, cfg["serving_ctx"] // 8),
        window=8)
    out["extra.decode_tokens_per_sec"] = s["decode_tokens_per_sec"]
    out["extra.serving_p99_ms"] = s["serving_p99_ms"]
    q, p = _serving_memory_benches(cfg)
    out["extra.kv_bytes_per_token"] = q["kv_bytes_per_token_ratio"]
    out["extra.prefix_prefill_savings"] = p["prefix_prefill_savings"]
    spec_runs, rb = _serving_compute_benches(cfg)
    if spec_runs:
        # the largest candidate K: the budget floor grades the
        # drafter's ceiling on the repetitive-suffix fixture
        out["extra.spec_accept_rate"] = \
            spec_runs[max(spec_runs)]["spec_accept_rate"]
    out["extra.batched_prefill_speedup"] = \
        rb["batched_prefill_speedup"]
    return out


# ---------------------------------------------------------------------------
# table assembly + decision-change demonstration
# ---------------------------------------------------------------------------

def build_table(records, topology: dict, backend: str,
                noise_pct: float, mode: str) -> dict:
    """Fold sweep records into one schema-versioned per-topology prefs
    doc (the layout ops/_dispatch.py selects by runtime topology)."""
    prefer, caps, pipeline, speedups = {}, {}, {}, {}
    fp8, quant, srv = {}, {}, {}
    for rec in records:
        if rec.get("space") == "routing" and rec.get("speedup") \
                is not None:
            speedups.setdefault(rec["family"], []).append(
                rec["speedup"])
        dec = rec.get("decision")
        if not dec:
            continue
        prefer.update(dec.get("prefer_pallas", {}))
        caps.update(dec.get("attn_block_cap", {}))
        pipeline.update(dec.get("pipeline", {}))
        fp8.update(dec.get("fp8", {}))
        quant.update(dec.get("quantization", {}))
        srv.update(dec.get("serving", {}))
    return {
        "schema": SCHEMA_VERSION,
        "methodology": "amortized",
        "source": "tools/autotune.py",
        "mode": mode,
        "backend": backend,
        "generated_at": _now(),
        "topology": topology,
        "noise_floor_pct": noise_pct,
        "prefer_pallas": prefer,
        "attn_block_cap": caps,
        "pipeline": pipeline,
        "fp8": fp8,
        "quantization": quant,
        "serving": srv,
        "speedups": {k: sorted(v) for k, v in speedups.items()},
        "sweep": {"records": records},
    }


def demonstrate_decision_changes(doc) -> list:
    """Install the table through the new accessor and report every
    dispatch decision it changes vs the uninstalled (file-backed /
    default) state — the proof the sweep's output actually steers.
    Restores the prior installed state."""
    from apex_tpu.ops import _dispatch

    prev = _dispatch._INSTALLED
    try:
        _dispatch.install_prefs(None)
        # probe a FIXED decision set (union of both tables' keys, so a
        # per-topology table that DROPS a default-table entry — back to
        # the design default — counts as the decision change it is)
        base = _dispatch.dispatch_tables()
        fams = sorted(set(doc.get("prefer_pallas", {}))
                      | set(base.prefer_pallas)
                      | {"multi_tensor", "welford", "attention"})
        dps = sorted(set(doc.get("attn_block_cap", {}))
                     | set(base.attn_block_cap))

        def snapshot():
            out = {}
            for f in fams:
                out[f"op_enabled:{f}"] = _dispatch.op_enabled(f)
            for dp in dps:
                out[f"attn_block_cap:{dp}"] = \
                    _dispatch.attn_block_cap(dp)
            out["pipeline:max_bucket_bytes"] = _dispatch.pipeline_pref(
                "max_bucket_bytes")
            out["pipeline:reduce_decompose"] = _dispatch.pipeline_pref(
                "reduce_decompose", "psum")
            out["fp8:amax_history_len"] = _dispatch.fp8_pref(
                "amax_history_len")
            out["fp8:interval"] = _dispatch.fp8_pref("interval")
            out["quantization:int8_dynamic"] = \
                _dispatch.quantization_pref("int8_dynamic", False)
            out["serving:page_size"] = _dispatch.serving_pref(
                "page_size")
            out["serving:decode_window"] = _dispatch.serving_pref(
                "decode_window")
            out["serving:kv_dtype"] = _dispatch.serving_pref(
                "kv_dtype", "f32")
            out["serving:prefix_share"] = _dispatch.serving_pref(
                "prefix_share", False)
            out["serving:spec_k"] = _dispatch.serving_pref(
                "spec_k", 0)
            out["serving:weight_dtype"] = _dispatch.serving_pref(
                "weight_dtype", "f32")
            out["serving:prefill_batch"] = _dispatch.serving_pref(
                "prefill_batch", 1)
            return out

        before = snapshot()
        _dispatch.install_prefs(doc)
        after = snapshot()
    finally:
        _dispatch._INSTALLED = prev
        _dispatch.invalidate_prefs_cache()
    return [{"decision": k, "before": before[k], "after": after[k]}
            for k in before if before[k] != after[k]]


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_SWEPT_FAMILIES = ("multi_tensor", "welford", "attention",
                   "attention_f32", "layer_norm", "softmax", "xentropy")


def run_sweep(cfg, out_dir: str, budget_path: str,
              install: bool) -> dict:
    """The whole pipeline: sweep -> per-topology table -> decision-
    change demonstration -> budget restamp.  Returns the summary dict
    (also written to <out>/autotune_summary.json)."""
    import jax

    from apex_tpu.ops import _dispatch
    from apex_tpu.platform import enable_compilation_cache, \
        select_platform
    select_platform()
    enable_compilation_cache()
    backend = jax.default_backend()
    topology = _dispatch.topology_block()
    _os.makedirs(out_dir, exist_ok=True)

    # pin every family to its Pallas path WHILE TIMING (kernel_bench
    # discipline: a previously written table must not make the
    # "kernel" leg silently measure the oracle)
    prev_pin = _os.environ.get("APEX_TPU_PREFER_PALLAS")
    _os.environ["APEX_TPU_PREFER_PALLAS"] = ",".join(_SWEPT_FAMILIES)
    try:
        noise_pct = measure_noise_floor(cfg)
        records = []
        records += sweep_routing(cfg, noise_pct, out_dir)
        records += sweep_attn_caps(cfg, noise_pct)
        records += sweep_pipeline_chunk(cfg, noise_pct, out_dir)
        records += sweep_reduce_decompose(cfg, noise_pct)
        records += sweep_fp8_cadence(cfg, noise_pct, out_dir)
        records += sweep_quantization(cfg, noise_pct)
        records += sweep_serving_geometry(cfg, noise_pct)
        records += sweep_serving_memory(cfg, noise_pct)
        records += sweep_serving_compute(cfg, noise_pct)
        budget_rows = measure_budget_rows(cfg)
    finally:
        if prev_pin is None:
            _os.environ.pop("APEX_TPU_PREFER_PALLAS", None)
        else:
            _os.environ["APEX_TPU_PREFER_PALLAS"] = prev_pin

    doc = build_table(records, topology, backend, noise_pct,
                      cfg["mode"])
    # the writer must never emit a table its own validator (and thus
    # check.sh) would reject
    errs = validate_table(doc, per_topology=True)
    if errs:
        raise RuntimeError(f"autotune produced an invalid table: {errs}")

    table_path = _os.path.join(out_dir,
                               f"dispatch_prefs.{topology['key']}.json")
    with open(table_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    # demonstrate BEFORE installing into the live ops directory: the
    # baseline snapshot must see the pre-sweep state, or an installed
    # run would compare the new table against itself (zero changes)
    changes = demonstrate_decision_changes(doc)
    installed_path = None
    if install:
        installed_path = _dispatch.topology_prefs_path(topology["key"])
        with open(installed_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        _dispatch.invalidate_prefs_cache()

    with open(budget_path, encoding="utf-8") as f:
        budget = json.load(f)
    when = _now()
    restamped = restamp_budget(
        budget, budget_rows, topology=topology["key"], backend=backend,
        noise_floor_pct=noise_pct, mode=cfg["mode"], when=when)
    budget_out = (budget_path if install
                  else _os.path.join(out_dir, "perf_budget.json"))
    with open(budget_out, "w") as f:
        json.dump(budget, f, indent=1, sort_keys=True)
        f.write("\n")

    summary = {"mode": cfg["mode"], "backend": backend,
               "topology": topology, "noise_floor_pct": noise_pct,
               "table": table_path, "installed": installed_path,
               "decision_changes": changes,
               "budget": budget_out, "budget_rows_restamped": restamped,
               "budget_measurements": budget_rows,
               "records": len(records)}
    with open(_os.path.join(out_dir, "autotune_summary.json"),
              "w") as f:
        json.dump({**summary, "sweep_records": records}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-topology kernel autotuner "
                    "(see module docstring)")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--cpu-smoke", action="store_true",
                      help="deterministic tiny sweep; writes table + "
                           "restamped budget copy into --out only")
    mode.add_argument("--full", action="store_true",
                      help="hardware sweep; installs the per-topology "
                           "table and restamps tools/perf_budget.json")
    mode.add_argument("--validate", nargs="*", metavar="FILE",
                      help="schema-check dispatch_prefs*.json "
                           "(default: every shipped table)")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="artifact directory (cpu-smoke writes here "
                         "INSTEAD of the repo tables)")
    ap.add_argument("--budget", default=BUDGET_PATH)
    args = ap.parse_args(argv)

    if args.validate is not None:
        errs = validate_paths(args.validate)
        if errs:
            for e in errs:
                print(f"autotune --validate: {e}", file=_sys.stderr)
            return 1
        if args.validate:
            n, suffix = len(args.validate), ""
        else:
            n = len(glob.glob(_os.path.join(
                _ROOT, "apex_tpu", "ops",
                "dispatch_prefs*.json"))) + 1
            suffix = " (incl. the apexcost cost ledger)"
        print(f"autotune --validate: {n} table(s) schema-valid{suffix}")
        return 0

    if args.cpu_smoke:
        # interpret-mode determinism: same kernels, no hardware needed
        _os.environ.setdefault("APEX_TPU_PALLAS_INTERPRET", "1")
        cfg = smoke_config()
        summary = run_sweep(cfg, args.out, args.budget, install=False)
    else:
        cfg = full_config()
        import jax

        from apex_tpu.platform import select_platform
        select_platform()
        if jax.default_backend() != "tpu":
            print(json.dumps({
                "error": "--full needs TPU hardware (interpret-mode "
                         "timings must never steer real dispatch); "
                         "use --cpu-smoke to exercise the plumbing",
                "backend": jax.default_backend()}))
            return 2
        summary = run_sweep(cfg, args.out, args.budget, install=True)

    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
