"""Benchmark: ResNet-50 ImageNet-shape training throughput, amp O2 +
FusedSGD (BASELINE.md north star — the reference's
examples/imagenet/main_amp.py config, synthetic data).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N}

vs_baseline compares against the A100 amp target named in BASELINE.json
(~2500 imgs/sec/chip for ResNet-50 AMP on DGX A100, the number the
north star says to get within 10% of).
"""

import json
import time

import jax
import jax.numpy as jnp

A100_IMGS_PER_SEC = 2500.0


def main():
    from apex_tpu import amp
    from apex_tpu.models import resnet50
    from apex_tpu.optimizers import FusedSGD

    on_tpu = jax.default_backend() not in ("cpu",)
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64
    steps = 20 if on_tpu else 3

    model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.key(0)
    x = jax.random.normal(rng, (batch, size, size, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.key(1), (batch,), 0, 1000)

    variables = model.init(jax.random.key(2), x, train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # amp O2: bf16 weights + f32 masters, static scale (bf16).  The
    # masters come from amp.initialize (cast from the ORIGINAL f32
    # init), not from re-upcasting the rounded bf16 params.
    params_bf16, amp_state = amp.initialize(params, opt_level="O2")
    opt = FusedSGD(params_bf16, lr=0.1, momentum=0.9, weight_decay=1e-4,
                   master_weights=True)
    opt.masters = amp_state.master_params

    def train_step(params, masters, opt_state, batch_stats, step, x, y):
        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x,
                train=True, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 1000, dtype=jnp.float32)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_masters, opt_state = opt.functional_step(
            masters, opt_state, grads, step)
        new_params = jax.tree_util.tree_map(
            lambda p, m: m.astype(p.dtype), params, new_masters)
        return new_params, new_masters, opt_state, new_stats, loss

    step_jit = jax.jit(train_step, donate_argnums=(0, 1, 2, 3))

    params_b = params_bf16
    masters = opt.masters
    opt_state = opt.opt_state
    stats = batch_stats

    # warmup (compile)
    for i in range(3):
        params_b, masters, opt_state, stats, loss = step_jit(
            params_b, masters, opt_state, stats, jnp.int32(i + 1), x,
            labels)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(steps):
        params_b, masters, opt_state, stats, loss = step_jit(
            params_b, masters, opt_state, stats, jnp.int32(i + 4), x,
            labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_amp_o2_fused_sgd_train_throughput",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(imgs_per_sec / A100_IMGS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
