"""Benchmark harness for the BASELINE.md tracked metrics.

Primary metric (north star): ResNet-50 ImageNet-shape training
throughput, amp O2 + FusedSGD (the reference's
examples/imagenet/main_amp.py config, synthetic data).
Secondary metric: BERT-Large FusedLAMB step time (BASELINE tracked
metric 2), reported in the same JSON line under "extra".

Always prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip",
   "vs_baseline": N, "backend": "tpu"|"cpu-fallback", ...}
The tracked metric name appears only on real TPU runs; off-TPU lines
are labeled "harness_check_cpu_fallback" (tiny proxy shapes prove the
harness, not performance).

Hardening (VERDICT.md round 1 Weak #1; restructured round 4): the
top-level process is a pure orchestrator that never imports jax.  It
runs the bench body in ONE watchdogged subprocess that is also the
FIRST AND ONLY tunnel client — no pre-probe, because the axon relay
admits only the first client after a relay restart (round-4 field
data in tools/artifacts/), so a throwaway probe burns the session the
bench needs.  The child detects a CPU-initialized backend itself and
relabels the run cpu-fallback; the orchestrator salvages flushed
intermediate lines if the child is killed, and emits a labeled
fallback/error line on any failure.  Every phase inside the child is
individually guarded too.

vs_baseline compares against the A100 amp target named in BASELINE.json
(~2500 imgs/sec/chip for ResNet-50 AMP on DGX A100, the number the
north star says to get within 10% of).
"""

import json
import os
import subprocess
import sys
import time
import traceback

A100_IMGS_PER_SEC = 2500.0


def _mfu(flops, step_s, on_tpu):
    """Cost-model MFU via the observatory's one chip-spec table
    (apex_tpu.telemetry.profiler.mfu) — the ad-hoc peak list that
    lived here moved there.  Reported only when the running chip is
    recognized; ``flops`` comes from the compiled step's own cost
    analysis, so wherever this is non-None the matching
    ``*_mfu_source`` extra reads "cost_analysis"."""
    if not (flops and on_tpu):
        return None
    try:
        from apex_tpu.telemetry.profiler.mfu import (device_peak_flops,
                                                     mfu as mfu_of)
        return mfu_of(flops, step_s, device_peak_flops())
    except Exception:
        return None


def _err(leg, stage, error):
    """One structured error entry: BENCH_r05 buried a flash_attention
    traceback in a string tail — failed legs are now machine-readable
    ({"leg", "stage", "error"}), and every consumer renders them via
    :func:`_err_str`."""
    return {"leg": leg, "stage": stage, "error": str(error)}


def _err_str(e):
    """Render one errors entry (dict or legacy string) for joins."""
    if isinstance(e, dict):
        return f"{e.get('leg')}[{e.get('stage')}]: {e.get('error')}"
    return str(e)

# NOTE: there is deliberately NO tunnel-probe helper here.  A
# timeout-killed jax.devices() subprocess is the documented tunnel
# wedge-maker, and the relay admits only the FIRST client after a
# restart (round-4 field data) — any probe burns the session the real
# workload needs.  Attempt the workload directly; the child relabels
# itself cpu-fallback when the TPU isn't granted.


def _resnet50_one_batch(jax, jnp, on_tpu, batch, size, steps):
    from apex_tpu import amp
    from apex_tpu.benchlib import chunked_train_bench
    from apex_tpu.models import resnet50
    from apex_tpu.optimizers import FusedSGD

    # space-to-depth stem on hardware: same function as the 7x7/s2
    # conv (tests pin numerical equality) but the MXU sees 12 input
    # channels instead of 3 — the MLPerf TPU ResNet transform.  MFU
    # caveat: cost analysis counts the folded kernel's 192 taps vs
    # the 7x7's 147 (structural zeros), reading ~1-2% high vs a
    # conv7x7 run at equal throughput; the 'stem' field records which
    # program the number belongs to
    model = resnet50(num_classes=1000, dtype=jnp.bfloat16,
                     stem_space_to_depth=on_tpu)
    rng = jax.random.key(0)
    x = jax.random.normal(rng, (batch, size, size, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.key(1), (batch,), 0, 1000)

    variables = model.init(jax.random.key(2), x, train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # amp O2: bf16 weights + f32 masters, static scale (bf16).  The
    # masters come from amp.initialize (cast from the ORIGINAL f32
    # init), not from re-upcasting the rounded bf16 params.
    params_bf16, amp_state = amp.initialize(params, opt_level="O2")
    masters0 = amp_state.master_params
    # Build the optimizer state from the amp masters directly
    # (master_weights=False: the functional path below threads masters
    # explicitly, and letting the ctor cast a second f32 master copy
    # would transiently double master memory).
    opt = FusedSGD(masters0, lr=0.1, momentum=0.9, weight_decay=1e-4,
                   master_weights=False)

    def train_step(params, masters, opt_state, batch_stats, step, x, y):
        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x,
                train=True, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 1000, dtype=jnp.float32)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_masters, opt_state = opt.functional_step(
            masters, opt_state, grads, step)
        new_params = amp.master_params_to_model_params(params, new_masters)
        return new_params, new_masters, opt_state, new_stats, loss

    r = chunked_train_bench(
        lambda c, step, x, y: train_step(c[0], c[1], c[2], c[3],
                                         step, x, y),
        (params_bf16, masters0, opt.opt_state, batch_stats,
         jnp.float32(0)),
        (x, labels), steps=steps, chunk=10 if on_tpu else steps,
        want_flops=on_tpu)
    float(r["state"][4])  # loss: forces the donated-buffer chain
    return {"imgs_per_sec": batch / r["step_ms"] * 1e3,
            "batch": batch, "image_size": size,
            "step_ms": r["step_ms"],
            "steps_per_dispatch": r["steps_per_dispatch"],
            "stem": "space_to_depth" if on_tpu else "conv7x7",
            # gradient-HANDLING provenance: "flat" = grads packed once
            # into dtype buckets and stepped by the flat kernels.  These
            # bf16/static-scale legs have no unscale/clip work, so the
            # fused unscale+norm+clip epilogue is NOT part of this
            # number — bench_amp_pipeline measures that separately
            # (amp_step_{flat,per_leaf}_ms extras).
            "amp_pipeline": "flat" if opt.fuse_buckets else "per_leaf",
            # tracked legs run with the metric ring OFF so the tracked
            # number stays comparable across rounds; the ring's cost is
            # quantified separately (telemetry_on/off extras)
            "telemetry": "off",
            "mfu": _mfu(r["flops_per_step"], r["step_ms"] / 1e3,
                        on_tpu)}


def bench_resnet50_amp_o2(jax, jnp, on_tpu):
    """North-star metric.  On hardware, batch is swept (the b128 MFU of
    0.25 in the round-4 window says the MXU is underfed; the reference
    target is imgs/sec/chip at the submitter's batch of choice) and the
    best throughput is reported, every candidate recorded in extra."""
    size = 224 if on_tpu else 64
    steps = 50 if on_tpu else 3
    best, sweep = None, {}
    for batch in ((128, 256) if on_tpu else (8,)):
        try:
            r = _resnet50_one_batch(jax, jnp, on_tpu, batch, size, steps)
        except Exception as e:  # e.g. OOM at the larger batch
            sweep[f"b{batch}_error"] = repr(e)[:200]
            continue
        sweep[f"b{batch}_imgs_per_sec"] = round(r["imgs_per_sec"], 2)
        if best is None or r["imgs_per_sec"] > best["imgs_per_sec"]:
            best = r
    if best is None:
        raise RuntimeError(f"resnet50: no batch size succeeded: {sweep}")
    best["batch_sweep"] = sweep
    return best


def _amp_lamb_train_bench(jax, jnp, model_loss, params0, batch, *,
                          steps, chunk, want_flops):
    """Shared amp-O2 + FusedLAMB benching scaffold: every BERT leg
    (tracked b8, b32 extra, packed-varlen extra) measures under ONE
    contract — O2 masters from amp.initialize, functional LAMB step,
    master→model copy-back, chunked dispatch."""
    from apex_tpu import amp
    from apex_tpu.benchlib import chunked_train_bench
    from apex_tpu.optimizers import FusedLAMB

    params_bf16, amp_state = amp.initialize(params0, opt_level="O2")
    masters0 = amp_state.master_params
    opt = FusedLAMB(masters0, lr=1e-3, weight_decay=0.01,
                    master_weights=False)

    def train_step(params, masters, opt_state, step, *b):
        loss, grads = jax.value_and_grad(model_loss)(params, *b)
        new_masters, opt_state = opt.functional_step(
            masters, opt_state, grads, step)
        new_params = amp.master_params_to_model_params(params, new_masters)
        return new_params, new_masters, opt_state, loss

    r = chunked_train_bench(
        lambda c, step, *b: train_step(c[0], c[1], c[2], step, *b),
        (params_bf16, masters0, opt.opt_state, jnp.float32(0)),
        batch, steps=steps, chunk=chunk, want_flops=want_flops)
    float(r["state"][3])  # loss: forces the donated-buffer chain
    # gradient-handling provenance only (see _resnet50_one_batch): the
    # fused unscale/clip epilogue is benched by bench_amp_pipeline
    r["amp_pipeline"] = "flat" if opt.fuse_buckets else "per_leaf"
    r["telemetry"] = "off"     # ring-on cost: telemetry_on/off extras
    return r


def _bert_lamb_one_batch(jax, jnp, on_tpu, batch, seq, steps, config):
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.bert import bert_large, BertModel

    if on_tpu:
        model = bert_large(dtype=jnp.bfloat16)
    else:
        model = BertModel(vocab_size=1024, hidden_size=128, num_heads=4,
                          num_layers=2, max_seq_len=128,
                          dtype=jnp.bfloat16)

    vocab = model.vocab_size
    tokens = jax.random.randint(jax.random.key(0), (batch, seq), 0, vocab)
    mlm_labels = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                    vocab)
    variables = model.init(jax.random.key(2), tokens)

    def loss_fn(p, tokens, labels):
        logits = model.mlm_logits({"params": p}, tokens)  # (s,b,V) f32
        flat = logits.transpose(1, 0, 2).reshape(-1, vocab)
        losses = softmax_cross_entropy_loss(
            flat, labels.reshape(-1), smoothing=0.0, padding_idx=-1)
        return jnp.mean(losses)

    r = _amp_lamb_train_bench(
        jax, jnp, loss_fn, variables["params"], (tokens, mlm_labels),
        steps=steps, chunk=10 if on_tpu else steps, want_flops=on_tpu)
    return {"step_ms": r["step_ms"], "config": config,
            "batch": batch, "seq": seq,
            "steps_per_dispatch": r["steps_per_dispatch"],
            "amp_pipeline": r.get("amp_pipeline"),
            "telemetry": r.get("telemetry", "off"),
            "mfu": _mfu(r["flops_per_step"], r["step_ms"] / 1e3,
                        on_tpu)}


def bench_bert_lamb(jax, jnp, on_tpu):
    """BERT-Large FusedLAMB step time (BASELINE tracked metric 2) at
    the fixed b8 s512 config (step-time numbers only compare at a
    fixed config).  The b32 throughput datapoint runs SEPARATELY in
    run_child, after this tracked metric has been flushed — a hang or
    watchdog kill during the extra must not lose a metric that already
    finished.

    On the cpu-fallback path a tiny proxy config runs instead (a real
    BERT-L CPU step takes minutes); the emitted dict carries the config
    so the two are never confused.
    """
    if not on_tpu:
        return _bert_lamb_one_batch(jax, jnp, False, 2, 64, 2,
                                    "tiny-cpu-proxy")
    return _bert_lamb_one_batch(jax, jnp, True, 8, 512, 20,
                                "bert-large b8 s512")


def bench_bert_packed_varlen(jax, jnp, model=None, rows=32, seq=512,
                             steps=20, chunk=10):
    """Packed-varlen vs padded-dense BERT throughput on REAL tokens
    (VERDICT r4 item 6: packing + flash + LAMB).  A synthetic varlen
    corpus (lengths seq/8..seq) is (a) FFD-packed into (rows, seq)
    rows via data.pack_sequences — segment-masked flash attention,
    per-sequence positions — and (b) naively padded one sequence per
    row.  Both train LAMB steps; the reported unit is real (non-pad)
    tokens per second, the number padding wastes.  TPU extra at
    BERT-L defaults; the tiny-model override is CPU-CI's."""
    import numpy as np

    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.data import pack_sequences
    from apex_tpu.models.bert import bert_large

    if model is None:
        model = bert_large(dtype=jnp.bfloat16)
    vocab = model.vocab_size
    rng = np.random.default_rng(11)
    seqs, packed = [], None
    while True:                       # enough sequences to fill rows
        seqs += [rng.integers(1, vocab, size=int(n))
                 for n in rng.uniform(seq // 8, seq, size=16)]
        packed = pack_sequences(seqs, max_len=seq, pad_id=0)
        if packed["tokens"].shape[0] >= rows:
            break
    pk = {k: jnp.asarray(v[:rows]) for k, v in packed.items()}
    real_packed = int(np.sum(packed["segment_ids"][:rows] > 0))

    out = {}
    for mode in ("packed", "dense"):
        if mode == "packed":
            tokens = pk["tokens"]
            seg, pos = pk["segment_ids"], pk["positions"]
            labels = jnp.where(seg > 0, jnp.asarray(
                rng.integers(0, vocab, size=tokens.shape),
                jnp.int32), -1)
            kw = dict(segment_ids=seg, positions=pos)
            real = real_packed
        else:
            lens = np.array([len(s) for s in seqs[:rows]])
            tokens = np.zeros((rows, seq), np.int32)
            for i, s in enumerate(seqs[:rows]):
                tokens[i, :len(s)] = s
            mask = jnp.asarray(
                np.arange(seq)[None, :] < lens[:, None])
            tokens = jnp.asarray(tokens)
            labels = jnp.where(mask, jnp.asarray(
                rng.integers(0, vocab, size=(rows, seq)),
                jnp.int32), -1)
            kw = dict(attention_mask=mask)
            real = int(lens.sum())

        variables = model.init(jax.random.key(2), tokens)

        def loss_of(p, tokens, labels, kw=kw):
            logits = model.mlm_logits({"params": p}, tokens, **kw)
            flat = logits.transpose(1, 0, 2).reshape(-1, vocab)
            losses = softmax_cross_entropy_loss(
                flat, labels.reshape(-1), smoothing=0.0,
                padding_idx=-1)
            keep = (labels.reshape(-1) >= 0)
            return jnp.sum(losses) / jnp.maximum(jnp.sum(keep), 1)

        r = _amp_lamb_train_bench(
            jax, jnp, loss_of, variables["params"], (tokens, labels),
            steps=steps, chunk=chunk, want_flops=False)
        out[f"bert_varlen_{mode}_step_ms"] = round(r["step_ms"], 2)
        out[f"bert_varlen_{mode}_real_tokens_per_sec"] = round(
            real / r["step_ms"] * 1e3, 1)
    out["bert_varlen_packed_speedup"] = round(
        out["bert_varlen_packed_real_tokens_per_sec"]
        / out["bert_varlen_dense_real_tokens_per_sec"], 2)
    return out


def bench_flash_attention(jax, jnp, on_tpu):
    """Flash kernel vs unfused XLA oracle (VERDICT r1 #3 done-criterion:
    kernel >= oracle at 2k; kernel handles 8k).  TPU only — interpret
    mode timings are meaningless.

    Every (shape, path) leg is guarded INDIVIDUALLY: BENCH_r05 lost all
    attention numbers to one remote-compile 500 on the first leg —
    a failed leg now records `flash_<s>[_oracle]_error` and the rest
    still measure; the same failures also come back structurally under
    `_errors` (popped by run_child into the report's errors list)."""
    from apex_tpu.benchlib import timeit as time_fn
    from apex_tpu.ops.attention import attention_ref, flash_attention

    out = {"_errors": []}
    # s=512 exercises the round-5 single-KV-block fast path (the shape
    # where round 4 measured the fwd losing); 2048 the generic online
    # kernel; 8192 the O(S)-memory story (oracle would need 48G)
    for s, run_oracle in ((512, True), (2048, True), (8192, False)):
        b, h, d = 4, 16, 128
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)

        def fwd_bwd(f):
            # all three grads returned so neither backward kernel is
            # dead-code-eliminated
            def g(q, k, v):
                return jax.grad(
                    lambda q, k, v: jnp.sum(
                        f(q, k, v).astype(jnp.float32)),
                    argnums=(0, 1, 2))(q, k, v)
            return jax.jit(g)

        # adaptive: the s=512 bodies are sub-ms — non-adaptive timing
        # would fold the relay RTT into exactly the flash-vs-oracle
        # ratio this leg exists to measure
        try:
            out[f"flash_{s}_fwdbwd_ms"] = round(time_fn(
                fwd_bwd(lambda q, k, v: flash_attention(q, k, v, True)),
                q, k, v, adaptive=True), 2)
        except Exception as e:
            out[f"flash_{s}_error"] = repr(e)[:200]
            out["_errors"].append(
                _err(f"flash_{s}", "fwd_bwd", repr(e)[:400]))
        if run_oracle:
            try:
                out[f"oracle_{s}_fwdbwd_ms"] = round(time_fn(
                    fwd_bwd(lambda q, k, v: attention_ref(q, k, v,
                                                          causal=True)),
                    q, k, v, adaptive=True), 2)
            except Exception as e:
                out[f"oracle_{s}_error"] = repr(e)[:200]
                out["_errors"].append(
                    _err(f"flash_{s}", "oracle", repr(e)[:400]))
    if not out["_errors"]:
        out.pop("_errors")
    return out


def bench_overlap_schedule(jax, jnp, steps=10, layers=16, hidden=256):
    """Interleaved vs trailing grad-reduce schedule, measured (ISSUE
    10): the SAME chunked-bucket flat-AMP DDP step under shard_map
    over every local device, once with the reduce-in-backward seam
    (``interleave=True``) and once trailing, each under a short
    observatory capture — ``overlap_pct`` (the hidden-collective
    fraction from telemetry/profiler/attribution.py) is the number the
    static ``amp.interleaved_flat_step`` spec promises and this leg
    verifies on hardware."""
    import shutil
    import tempfile

    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import amp, comm
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.bucketing_bench import many_leaf_params
    from apex_tpu.telemetry.profiler import build_report, capture

    devs = jax.devices()
    mesh = Mesh(np.array(devs), (comm.AXIS_DATA,))
    params = many_leaf_params(jax, jnp, layers, hidden)
    n_bytes = sum(int(l.size) * l.dtype.itemsize
                  for l in jax.tree_util.tree_leaves(params))
    scaler = amp.LossScaleState.create(2.0 ** 12)
    x = jax.random.normal(jax.random.key(1),
                          (8 * len(devs), hidden), jnp.float32)

    def loss_fn(p, x):
        h = x
        for k in sorted(p):
            h = jnp.tanh(h @ p[k]["w"] + p[k]["b"]) \
                * p[k]["scale"] + p[k]["shift"]
        return jnp.mean(h ** 2)

    out = {"overlap_devices": len(devs)}
    for label, interleave in (("interleaved", True), ("trailing", False)):
        # ~4 chunks: multiple per-bucket collectives to hide
        opt = FusedAdam(params, lr=1e-3,
                        max_bucket_bytes=max(1, n_bytes // 4))
        pipe = amp.FlatGradPipeline(
            optimizer=opt, max_grad_norm=1.0,
            axis_name=comm.AXIS_DATA, interleave=interleave)
        hypers = {k: jnp.asarray(v, jnp.float32)
                  for k, v in opt.hypers.items()
                  if isinstance(v, float)}

        def step_fn(work, opt_state, x, step):
            ptree = pipe.plan.unpack(work)
            loss, flat = pipe.scaled_value_and_grad(
                loss_fn, scaler, ptree, x)
            new_w, _, new_s = opt._full_step_flat(
                work, None, opt_state, flat.bufs, step, 1.0,
                hypers, flat.found_inf)
            return loss, new_w, new_s

        # interleaved vs trailing are two programs by design
        # apexlint: disable-next=APX302
        jstep = jax.jit(comm.shard_map(
            step_fn, mesh,
            in_specs=(P(), P(), P(comm.AXIS_DATA), P()),
            out_specs=P()), donate_argnums=(1,))
        work, state = opt._param_bufs, opt.opt_state
        # warmup OUTSIDE the window (capture.py's rule)
        loss, work, state = jstep(work, state, x, jnp.int32(1))
        jax.block_until_ready(loss)
        tdir = tempfile.mkdtemp(prefix="apex_tpu_overlap_")
        try:
            with capture.trace(tdir):
                for i in range(steps):
                    loss, work, state = jstep(work, state, x,
                                              jnp.int32(2 + i))
                jax.block_until_ready(loss)
            rep = build_report(tdir, steps=steps)
            if not rep.get("error"):
                out[f"overlap_{label}_pct"] = rep.get("overlap_pct")
                out[f"overlap_{label}_step_ms"] = (
                    rep["breakdown"].get("step_ms"))
        finally:
            shutil.rmtree(tdir, ignore_errors=True)
        out["overlap_buckets"] = len(opt._plan.buckets)
    return out


NORTH_STAR_METRIC = "resnet50_amp_o2_fused_sgd_train_throughput"


def _metric_name(backend):
    """VERDICT r3 #6: the tracked metric name is reserved for REAL TPU
    measurements.  Off-TPU the tiny proxy shape only proves the harness
    runs end-to-end, and three rounds of 4-ish imgs/sec under the
    north-star name read like a measurement — label it as the liveness
    check it is."""
    return (NORTH_STAR_METRIC if backend == "tpu"
            else "harness_check_cpu_fallback")


def _empty_result(backend="unknown"):
    return {
        "metric": _metric_name(backend),
        "value": 0.0,
        "unit": "imgs/sec/chip",
        "vs_baseline": 0.0,
        "backend": backend,
        "extra": {},
        "errors": [],
    }


def _dump(out):
    """One JSON line, with an empty errors list elided."""
    return json.dumps({k: v for k, v in out.items()
                       if k != "errors" or v})


def _stamp_measured_at(out):
    """Capture timestamp on the final bench line.  perf_gate's
    auto-gating compares this against the budget's ``stamped_at`` to
    decide report-vs-gate, so a live hardware round that does not
    carry it can never arm the gate (the cached fallback serves its
    original window's stamp as ``extra.cached_measured_at`` instead —
    see _cached_tpu_result)."""
    out.setdefault("measured_at", time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    return out


def run_child(backend):
    """Bench body; prints one JSON line.  backend: "tpu"|"cpu"|"cpu-fallback"."""
    out = _empty_result(backend)
    on_tpu = backend == "tpu"
    try:
        import jax
        # Persistent executable cache: repeat bench runs skip the
        # multi-minute first compile of the train steps.
        from apex_tpu.platform import enable_compilation_cache, \
            select_platform
        enable_compilation_cache()
        select_platform()  # honor APEX_TPU_PLATFORM (e.g. cpu): skip
        #                    the ~25-min hung-tunnel init when the
        #                    operator already knows there's no TPU
        if on_tpu:
            # arm the latency-hiding scheduler BEFORE the first
            # backend use and record what was set: the measured
            # overlap fractions below must name the schedule they ran
            # under (a no-op + warning if something already
            # initialized the backend)
            try:
                from apex_tpu.platform import \
                    enable_latency_hiding_scheduler
                out["extra"]["lhs_flags"] = \
                    enable_latency_hiding_scheduler(target="tpu")
            except Exception as e:
                out["errors"].append(_err("lhs_flags", "arm", repr(e)))
        if not on_tpu:
            # sitecustomize force-registers the axon TPU plugin; env vars
            # are too late once jax is imported, so flip the live config
            # instead (must happen before the first backend use).
            jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        actual = jax.default_backend()
        if on_tpu and actual == "cpu":
            # jax silently fell back to CPU — don't mislabel CPU numbers
            # as a TPU result.
            out["backend"] = backend = "cpu-fallback"
            out["metric"] = _metric_name(backend)
            on_tpu = False
            out["errors"].append(
                _err("backend", "init", "requested tpu but jax "
                                        "initialized cpu"))
    except Exception as e:
        out["errors"].append(_err("jax-init", "init", repr(e)))
        print(_dump(out))
        return

    try:
        from apex_tpu.benchlib import dispatch_overhead_ms
        out["extra"]["dispatch_overhead_ms"] = round(
            dispatch_overhead_ms(), 3)
    except Exception as e:
        out["errors"].append(_err("dispatch_overhead", "measure",
                                  repr(e)))

    try:
        r = bench_resnet50_amp_o2(jax, jnp, on_tpu)
        out["value"] = round(r["imgs_per_sec"], 2)
        out["vs_baseline"] = round(r["imgs_per_sec"] / A100_IMGS_PER_SEC,
                                   4)
        out["extra"]["resnet50_step_ms"] = round(r["step_ms"], 2)
        out["extra"]["resnet50_batch"] = r["batch"]
        out["extra"]["resnet50_image_size"] = r["image_size"]
        out["extra"]["resnet50_steps_per_dispatch"] = r.get(
            "steps_per_dispatch")
        out["extra"]["resnet50_batch_sweep"] = r.get("batch_sweep")
        out["extra"]["resnet50_stem"] = r.get("stem")
        out["extra"]["resnet50_amp_pipeline"] = r.get("amp_pipeline")
        out["extra"]["resnet50_telemetry"] = r.get("telemetry")
        if r.get("mfu") is not None:
            out["extra"]["resnet50_mfu"] = r["mfu"]
            # provenance: flops from the compiled step's cost analysis
            # over the profiler.mfu chip table (docs/perf.md)
            out["extra"]["resnet50_mfu_source"] = "cost_analysis"
    except Exception:
        out["errors"].append(_err(
            "resnet50", "train_bench",
            traceback.format_exc(limit=3).replace("\n", " | ")))

    # Flush the primary metric NOW: if the secondary bench hangs and the
    # watchdog kills us, the orchestrator salvages the last parseable
    # line, so the north-star number survives.
    print(_dump(out), flush=True)

    try:
        b = bench_bert_lamb(jax, jnp, on_tpu)
        out["extra"]["bert_large_fused_lamb_step_ms"] = round(
            b["step_ms"], 2)
        out["extra"]["bert_config"] = b["config"]
        out["extra"]["bert_amp_pipeline"] = b.get("amp_pipeline")
        out["extra"]["bert_telemetry"] = b.get("telemetry")
        if b.get("mfu") is not None:
            out["extra"]["bert_mfu"] = b["mfu"]
            out["extra"]["bert_mfu_source"] = "cost_analysis"
    except Exception:
        out["errors"].append(_err(
            "bert_lamb", "train_bench",
            traceback.format_exc(limit=3).replace("\n", " | ")))

    # extras AFTER both tracked metrics are flushed: a hang + watchdog
    # kill in here truncates only the extras.  flash (a VERDICT
    # done-criterion) runs BEFORE the OOM-prone b32 leg so a hang
    # there can't truncate it.
    if on_tpu:
        print(_dump(out), flush=True)
        try:
            fa = bench_flash_attention(jax, jnp, on_tpu)
            # per-leg failures come back structurally (satellite of
            # the observatory PR): keep the flash_*_error extras for
            # continuity AND surface the legs in errors
            out["errors"].extend(fa.pop("_errors", []))
            out["extra"].update(fa)
        except Exception:
            out["errors"].append(_err(
                "flash_attention", "bench",
                traceback.format_exc(limit=3).replace("\n", " | ")))

        print(_dump(out), flush=True)
        try:
            # per-leaf vs bucketed fused-optimizer step on a many-leaf
            # pytree (the dispatch-amortization win the bucketed flat
            # path exists for; amortized on-device timing)
            from apex_tpu.optimizers.bucketing_bench import \
                bench_optimizer_bucketing
            r = bench_optimizer_bucketing()
            out["extra"].update({k: v for k, v in r.items()
                                 if k != "optim_buckets"})
        except Exception as e:
            out["extra"]["optim_bucketing_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # full AMP gradient epilogue, flat pipeline vs per-leaf amp
            # ops on the same many-leaf tree (the pack-once +
            # fused-unscale/norm/clip win this PR exists for)
            from apex_tpu.optimizers.bucketing_bench import \
                bench_amp_pipeline
            out["extra"].update(bench_amp_pipeline())
        except Exception as e:
            out["extra"]["amp_pipeline_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # metric ring on vs off over the identical flat-AMP step —
            # quantifies BENCH_r06's telemetry cost claim (target
            # telemetry_overhead_pct <= ~2)
            from apex_tpu.telemetry.bench import bench_telemetry_overhead
            out["extra"].update(bench_telemetry_overhead())
        except Exception as e:
            out["extra"]["telemetry_overhead_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # grad-accum train legs: per-leaf vs flat accumulation at
            # N_micro in {1,4,8} (the fused flat_accumulate path this
            # round ships)
            from apex_tpu.optimizers.bucketing_bench import \
                bench_grad_accum
            out["extra"].update(bench_grad_accum())
        except Exception as e:
            out["extra"]["grad_accum_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # interleaved vs trailing grad-reduce schedule: a short
            # observatory capture of the SAME chunked-bucket DDP step
            # both ways — overlap_pct is the runtime ground truth of
            # the amp.interleaved_flat_step spec's static promise
            out["extra"].update(bench_overlap_schedule(jax, jnp))
        except Exception as e:
            out["extra"]["overlap_schedule_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # fp8 vs bf16 fused_dense fwd+bwd: grounds the
            # extra.fp8_matmul_speedup perf-budget row (floor 1.5 on
            # fp8-capable chips; graded no-data until this lands)
            from apex_tpu.amp.fp8_bench import bench_fp8_matmul
            out["extra"].update(bench_fp8_matmul())
        except Exception as e:
            out["extra"]["fp8_matmul_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # serving engine end-to-end: grounds the
            # extra.decode_tokens_per_sec / extra.serving_p99_ms
            # perf-budget rows (graded no-data until this lands)
            from apex_tpu.serving.bench import bench_serving
            out["extra"].update(bench_serving(
                n_requests=16, n_layers=4, hidden=256, n_heads=8,
                max_slots=8, page_size=16, pages_per_slot=8,
                window=16, max_new_tokens=64))
        except Exception as e:
            out["extra"]["serving_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # BERT-L at b32: the throughput/MFU story (b8 ran at MFU
            # 0.34; larger batches amortize fixed per-step work)
            r32 = _bert_lamb_one_batch(jax, jnp, True, 32, 512, 20,
                                       "bert-large b32 s512")
            out["extra"]["bert_b32_step_ms"] = round(r32["step_ms"], 2)
            out["extra"]["bert_b32_tokens_per_sec"] = round(
                32 * 512 / r32["step_ms"] * 1e3, 1)
            if r32.get("mfu") is not None:
                out["extra"]["bert_b32_mfu"] = r32["mfu"]
        except Exception as e:
            # e.g. OOM — recorded in extra, NOT in errors: a failed
            # EXTRA must not block the validator's bench stamp when
            # both tracked metrics landed clean
            out["extra"]["bert_b32_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # packed-varlen vs padded-dense on real tokens (the
            # padding-waste story packing exists to fix)
            out["extra"].update(bench_bert_packed_varlen(jax, jnp))
        except Exception as e:
            out["extra"]["bert_varlen_error"] = repr(e)[:200]

        print(_dump(out), flush=True)
        try:
            # observatory capture: a short device-only trace of the
            # north-star step, attributed into compute / collective /
            # transfer / idle — lands the collective-overlap fraction
            # (ROADMAP item 2's target gauge) next to the throughput
            # it explains.  Reuses the persistent-cache-warm step, so
            # the cost is ~10 traced steps, not a fresh compile.
            import shutil
            import tempfile

            from apex_tpu.telemetry.profiler import build_report, capture
            tdir = tempfile.mkdtemp(prefix="apex_tpu_bench_trace_")
            try:
                # warmup OUTSIDE the window (capture.py's rule): this
                # identical un-traced leg populates the persistent
                # compilation cache, so the traced call below pays a
                # cache-hit compile (ms), not the cold multi-minute
                # XLA build the window would otherwise record as idle
                _resnet50_one_batch(jax, jnp, on_tpu, 128, 224, 10)
                with capture.trace(tdir):
                    _resnet50_one_batch(jax, jnp, on_tpu, 128, 224, 10)
                # chunked_train_bench dispatches a warmup chunk (10
                # steps) before the timed chunk INSIDE this window, so
                # the device timeline holds 20 executed steps (plus
                # one init pass) — the breakdown/overlap fractions
                # are the product here, but the per-step divisor must
                # match what ran
                rep = build_report(tdir, steps=20)
                if not rep.get("error"):
                    bd = rep["breakdown"]
                    out["extra"]["resnet50_overlap_pct"] = rep.get(
                        "overlap_pct")
                    out["extra"]["resnet50_breakdown"] = {
                        k: bd.get(k)
                        for k in ("compute_ms", "collective_ms",
                                  "transfer_ms", "idle_ms")}
            finally:
                # the attribution above is the product; the raw trace
                # is waste once read (tools/profile_step.py is the
                # keep-the-trace capture path)
                shutil.rmtree(tdir, ignore_errors=True)
        except Exception as e:
            out["extra"]["resnet50_profile_error"] = repr(e)[:200]

    print(_dump(_stamp_measured_at(out)), flush=True)


def _cached_tpu_result(path=None):
    """The most recent committed hardware measurement
    (tools/artifacts/bench_tpu.json), relabeled backend "tpu-cached"
    with its capture time, or None.  Only a clean real-TPU line
    qualifies (backend tpu, positive value)."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "artifacts", "bench_tpu.json")
    try:
        with open(path) as f:
            cached = json.load(f)
        if (cached.get("backend") != "tpu"
                or float(cached.get("value", 0)) <= 0):
            return None
        cached["backend"] = "tpu-cached"
        # the capture session's own errors describe THAT session (and
        # can carry multi-KB ANSI tracebacks); keep a prefixed stub so
        # a reader cannot mistake them for THIS report's failures
        # (entries may be structured {leg, stage, error} dicts or
        # legacy strings — stringify both)
        cached["errors"] = ["captured: " + _err_str(e)[:150]
                            for e in cached.get("errors", [])]
        # capture time: the validator embeds measured_at at write time;
        # mtime is only a fallback (it is checkout time on a fresh
        # clone, not capture time)
        measured = cached.pop("measured_at", None) or time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path)))
        cached.setdefault("extra", {})["cached_measured_at"] = measured
        return cached
    except Exception:
        return None


def _env_float(name, default):
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


def _last_json_line(stdout):
    """Last parseable JSON object line in a child's stdout, or None."""
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and "metric" in out:
                return out
        except ValueError:
            continue
    return None


def _run_bench_child(backend, timeout_s):
    """Returns (result-dict or None, error-string or None).

    A salvaged-but-abnormal child (nonzero rc, or killed by the
    watchdog after flushing the intermediate line) gets the abnormality
    appended to the result's errors so a missing secondary metric is
    distinguishable from a never-attempted one.
    """
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", backend],
            timeout=timeout_s, capture_output=True, text=True)
        stdout, stderr = r.stdout, r.stderr
        note = None if r.returncode == 0 else f"rc={r.returncode}"
    except subprocess.TimeoutExpired as e:
        def _s(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else b
        stdout, stderr = _s(e.stdout), _s(e.stderr)
        note = f"timeout after {timeout_s}s"
    except Exception as e:
        return None, f"child: {e!r}"
    out = _last_json_line(stdout)
    if out is not None:
        if note is not None:
            out.setdefault("errors", []).append(
                _err("child", "watchdog", note))
        return out, None
    tail = (stderr or "").strip()[-300:]
    return None, (f"child: {note or 'exited'}, no JSON on stdout, "
                  f"stderr tail: {tail!r}")


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(sys.argv[2])
        return

    force_cpu = (os.environ.get("APEX_TPU_BENCH_FORCE_CPU", "")
                 .lower() not in ("", "0", "false"))
    # NO pre-probe (round-4 field data, tools/artifacts/): the axon
    # relay admits only the FIRST client after a relay restart, so a
    # throwaway jax.devices() probe BURNS the session the bench child
    # then needs, and a timeout-killed probe is the documented tunnel
    # wedge-maker.  The child is the first and only client: it checks
    # jax.default_backend() itself and relabels the run cpu-fallback
    # (tiny proxy shapes) when the TPU isn't granted — a stalled init
    # resolves inside the child (~25 min observed plugin give-up)
    # without anything being killed.
    on_tpu = not force_cpu
    backend = "tpu" if on_tpu else "cpu"

    # Leash covers a worst-case init stall (~25 min) plus the bench
    # itself — now including the b256 ResNet and b32 BERT sweep legs
    # (each a fresh multi-minute remote compile); the child flushes
    # each tracked metric as it lands, so even a late hang+kill
    # salvages everything already measured.
    child_timeout = _env_float("APEX_TPU_BENCH_CHILD_TIMEOUT",
                               3900.0 if on_tpu else 1500.0)
    out, err = _run_bench_child(backend, child_timeout)
    # A TPU child that errored fast (value-0 line) OR that initialized
    # CPU and relabeled itself cpu-fallback did NOT measure hardware —
    # both fall through to the cached-window / CPU-proxy ladder.
    tpu_failed = backend == "tpu" and (
        out is None or float(out.get("value", 0)) <= 0
        or out.get("backend") != "tpu")
    if out is not None and not tpu_failed:
        print(json.dumps(out))
        return

    if backend == "tpu":
        # TPU child hung/crashed/zeroed — before degrading to the CPU
        # proxy, surface the most recent REAL hardware measurement if
        # one exists (tools/artifacts/bench_tpu.json, written by the
        # one-session validator inside a tunnel window).  Clearly
        # labeled: backend "tpu-cached" + the capture timestamp — a
        # recorded chip number with honest provenance beats a
        # meaningless CPU-proxy line when the tunnel happens to be
        # down at report time.
        if out is not None:
            err = "; ".join(["tpu child did not measure hardware"]
                            + [_err_str(e)
                               for e in out.get("errors", [])])
        cached = _cached_tpu_result()
        if cached is not None:
            cached.setdefault("errors", []).append(_err(
                "orchestrator", "fallback",
                f"live tpu attempt failed ({err}); value is the "
                f"round's recorded hardware window"))
            print(json.dumps(cached))
            return
        # no cached hardware number: a CPU-proxy liveness line.  The
        # failed child may itself BE that line (it initialized CPU and
        # ran the proxy shapes) — reuse it rather than re-running.
        if (out is not None and out.get("backend") == "cpu-fallback"
                and float(out.get("value", 0)) > 0):
            cpu_out, err2 = dict(out), None
            # fresh errors list + short note: the joined `err` above
            # CONTAINS this same errors list, so appending it back
            # onto the shared (aliased) list would double every entry
            cpu_out["errors"] = list(out.get("errors", []))
            err = "tpu child did not measure hardware (ran cpu-fallback)"
        else:
            cpu_out, err2 = _run_bench_child("cpu-fallback",
                                             child_timeout)
        if cpu_out is not None:
            cpu_out.setdefault("errors", []).append(
                _err("orchestrator", "tpu_attempt", err))
            if out is not None and cpu_out is not out \
                    and cpu_out.get("extra") is not out.get("extra"):
                # Keep any metric the TPU child DID measure (e.g. BERT
                # succeeded while ResNet OOMed) — real-hardware numbers
                # beat the CPU proxy.
                for k, v in out.get("extra", {}).items():
                    cpu_out.setdefault("extra", {})[f"tpu_{k}"] = v
            print(json.dumps(cpu_out))
            return
        err = f"{err}; cpu-retry: {err2}"

    out = _empty_result(backend)
    out["errors"].append(_err("orchestrator", "run", err))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
